"""Auto-sharding planner: rule-driven PartitionSpec layouts priced by
the comms cost model and gated by the device-memory plane.

ROADMAP item 1 ("close the loops and remove the hand-placement"): the
parallel plane has dp/sp/ep meshes, ZeRO via ``ReduceStrategy.Reduce``,
ring attention and a calibrated comms cost model — but the user still
hand-places every axis.  This module takes an **unannotated** Program
and emits a full dp x fsdp x tp sharding:

1. **Rules.**  ``match_partition_rules(rules, params)`` matches each
   parameter (name, shape) against an ordered ``[(regex, spec)]`` list
   — the first hit wins; spec entries may be callables ``(name, shape)
   -> PartitionSpec | None`` so one rule can split column-parallel
   (out >= in) from row-parallel (out < in) fc weights by shape.  The
   built-in ``default_rules()`` cover the transformer/BERT/GPT
   parameter naming this repo's layers produce (``fc_N.w_K``,
   ``embedding_N.w_K`` / ``gpt_wte``, ``moe_N.w_K``, ``layer_norm`` /
   biases / conv kernels replicated).  Specs are validated against the
   actual mesh: axes absent (or size 1) degrade to replication, as do
   indivisible dims — one rule set runs on any mesh
   (``parallel_executor._hint_to_spec`` semantics).

2. **Priced candidates.**  ``build_plan`` enumerates every
   (dp, fsdp, tp) factorization of the device count and prices each
   candidate's per-step collective schedule with
   ``comms.model_predict`` over the calibrated ``comms_model.json``
   (arXiv:2110.10548's cost-model-driven synthesis): gradient
   allreduce over the replicated extent, fsdp weight allgather +
   gradient reduce-scatter, tp activation allreduce per row-parallel
   weight, plus a compute proxy that rewards batch sharding.  A
   missing/partial model NEVER fails the plan: the affected term
   degrades to heuristic byte-count pricing and is counted
   (``parallel/plan_unpriced`` — the PR-8 ``comms/plan_unpriced``
   honesty convention).

3. **HBM gate.**  Each candidate's per-device residency (params +
   grads + optimizer moments under the candidate's sharding +
   activation proxy) is checked against the memviz budget
   (``memviz.budget_bytes()`` / ``FLAGS_memviz_budget_bytes``) BEFORE
   anything compiles; when the program already has a per-program peak
   attribution row (``memviz.peak_bytes``), the measured peak
   calibrates the activation term.  Over-budget layouts are rejected
   (``parallel/plan_hbm_rejected``) and never traced.

4. **Weight-update sharding** (arXiv:2004.13336, "Automatic
   Cross-Replica Sharding of Weight Update Computation"): the chosen
   plan names an ``update_axis`` and the runner applies it through the
   EXISTING ZeRO path (``CompiledProgram.with_sharded_optimizer_states``
   / ``_shard_opt_states_axis`` — the ``ReduceStrategy.Reduce``
   rendering), not a parallel implementation: optimizer accumulators
   shard over the fsdp axis when one exists, else over dp.

**Fingerprint honesty.**  ``digest()`` folds the flag, the rule-set
identity, the comms-model identity and the power-of-two-bucketed HBM
budget into a string both runners add to their segment fingerprints
(the ``comms_plan.digest()`` pattern): a flag/model/budget change
retraces exactly once, an unchanged plan never retraces — and the
chosen specs themselves already key the executable via the runners'
``repr(in_shardings)`` fingerprint component.

Observability: ``parallel/plan_*`` counters, ``parallel/plan_layout_*``
gauges, a bounded per-program plan registry ``report()`` renders as the
``/statusz`` ``auto_shard`` section, and ``stat_summary.py
--autoshard`` offline.

No jax imports at module level (hot-path discipline, like comms_plan);
planning runs once per (program, mesh), never per step.
"""

import hashlib
import re
import threading
import time

import numpy as np

from ..fluid import monitor
from ..fluid.flags import get_flag

__all__ = [
    'SpecLayout', 'default_rules', 'match_partition_rules',
    'validate_spec', 'enumerate_layouts', 'build_plan', 'Plan',
    'enabled', 'digest', 'plan_for', 'choose_mesh', 'report', 'reset',
]

_lock = threading.Lock()
# program label -> plan summary (bounded, insertion-ordered): the
# /statusz auto_shard section
_PLANS = {}
_PLANS_CAP = 64

# params below this many bytes are never worth scattering (the
# allgather latency dwarfs the residency win)
MIN_SHARD_BYTES = 1024
# compute proxy: seconds per (param element x token) of matmul work —
# only the RANKING between candidates matters, not the absolute scale
_FLOP_SECONDS = 1.0 / 1e12
# heuristic byte pricing when the cost model has no entry for a
# collective: a flat launch latency plus wire bytes at a nominal
# fabric bandwidth (the "byte-count pricing" fallback)
_HEUR_LATENCY_S = 20e-6
_HEUR_BW_BYTES_PER_S = 10e9
# grads are transient but alive alongside params at the update;
# optimizer moments counted per _opt_state_multiplier
_ACT_BYTES_PER_TOKEN_FACTOR = 2.0   # fwd + bwd activation residency


def reset():
    """Drop the plan registry (tests)."""
    with _lock:
        _PLANS.clear()


def enabled():
    return bool(get_flag('FLAGS_auto_shard', False))


# ------------------------------------------------------------ rule layer
class SpecLayout(object):
    """Canonical PartitionSpecs aligned with this repo's mesh axes
    (SNIPPETS.md [2]): data batch on 'dp', parameter scatter on
    'fsdp', tensor parallelism on 'mp' (the repo's model axis)."""

    __slots__ = ('data_axis', 'fsdp_axis', 'tp_axis')

    def __init__(self, data_axis='dp', fsdp_axis='fsdp', tp_axis='mp'):
        self.data_axis = data_axis
        self.fsdp_axis = fsdp_axis
        self.tp_axis = tp_axis

    def _ps(self, *spec):
        from jax.sharding import PartitionSpec as P
        return P(*spec)

    def embedding(self):
        """Embedding tables: vocab rows scattered over fsdp x tp."""
        return self._ps((self.fsdp_axis, self.tp_axis), None)

    def col_weight(self):
        """Column-parallel fc (qkv / ffn-up / lm head): rows on fsdp,
        output columns on tp."""
        return self._ps(self.fsdp_axis, self.tp_axis)

    def row_weight(self):
        """Row-parallel fc (attention out / ffn-down): input rows on
        tp, columns on fsdp."""
        return self._ps(self.tp_axis, self.fsdp_axis)

    def expert_weight(self):
        """3D expert stacks [E, ...]: experts scattered over fsdp (an
        'ep' mesh hint, when present, takes precedence in the
        runner)."""
        return self._ps(self.fsdp_axis, None, None)

    def replicated(self):
        return None


def default_rules(layout=None):
    """The built-in rule set for this repo's layer naming (LayerHelper
    generates ``<layer>_N.w_K``; gpt names its tied embedding
    ``gpt_wte``).  Ordered; first match wins; a None result falls
    through to the next rule."""
    lay = layout or SpecLayout()

    def fc_weight(name, shape):
        if len(shape) != 2:
            return None
        rows, cols = int(shape[0]), int(shape[1])
        if rows <= 1 or cols <= 1:
            return None
        # column-parallel when the layer widens (qkv 3h, ffn 4h,
        # vocab head), row-parallel when it narrows back
        return lay.col_weight() if cols >= rows else lay.row_weight()

    def embed_weight(name, shape):
        return lay.embedding() if len(shape) == 2 else None

    def expert_weight(name, shape):
        return lay.expert_weight() if len(shape) == 3 else None

    return [
        (r'gpt_wte|embedding_\d+\.w_\d+', embed_weight),
        (r'moe[\w.]*\.w_\d+', expert_weight),
        (r'(fc|mul)_\d+\.w_\d+', fc_weight),
        # norms, biases, conv kernels, scalars: replicated
        (r'.*', lambda name, shape: None),
    ]


def validate_spec(spec, shape, axis_sizes):
    """Degrade a PartitionSpec to what `axis_sizes` ({axis: size}) and
    `shape` admit: axes absent or size 1 drop, a dim whose kept-axes
    product does not divide it replicates — the _hint_to_spec contract,
    so one rule set runs on any mesh.  Returns a PartitionSpec or None
    (fully replicated)."""
    if spec is None:
        return None
    from jax.sharding import PartitionSpec as P
    out = []
    for dim, entry in zip(tuple(shape), tuple(spec)):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        keep = [a for a in axes if int(axis_sizes.get(a, 1)) > 1]
        prod = 1
        for a in keep:
            prod *= int(axis_sizes[a])
        if keep and int(dim) > 0 and int(dim) % prod == 0:
            out.append(tuple(keep) if len(keep) > 1 else keep[0])
        else:
            out.append(None)
    # pad unmentioned trailing dims as replicated
    while len(out) < len(tuple(shape)):
        out.append(None)
    if all(e is None for e in out):
        return None
    return P(*out)


def match_partition_rules(rules, params, axis_sizes=None):
    """{name: PartitionSpec | None} for `params` ([(name, shape)] or
    {name: shape}) under ordered `rules` ([(regex, PartitionSpec or
    callable(name, shape))]).  Scalars and single-element params are
    never partitioned (SNIPPETS.md [3]).  With `axis_sizes` the specs
    are validated/degraded against that mesh."""
    if isinstance(params, dict):
        params = list(params.items())
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out = {}
    for name, shape in params:
        shape = tuple(int(s) for s in (shape or ()))
        spec = None
        if shape and int(np.prod([max(s, 1) for s in shape])) > 1:
            for pat, rule in compiled:
                if pat.search(name) is None:
                    continue
                spec = rule(name, shape) if callable(rule) else rule
                if spec is not None:
                    break
        if axis_sizes is not None:
            spec = validate_spec(spec, shape, axis_sizes)
        out[name] = spec
    return out


# ------------------------------------------------------- program inventory
def _param_inventory(program):
    """[(name, shape, nbytes, itemsize)] for the program's parameters
    (static shapes; -1 dims never appear on params)."""
    out = []
    for p in program.all_parameters():
        shape = tuple(int(s) for s in (getattr(p, 'shape', ()) or ()))
        try:
            dt = np.dtype(p.dtype)
        except Exception:
            dt = np.dtype('float32')
        elems = int(np.prod([max(s, 1) for s in shape])) if shape else 1
        out.append((p.name, shape, elems * dt.itemsize, dt.itemsize))
    return out


_OPT_STATES = {'sgd': 0, 'momentum': 1, 'lars_momentum': 1,
               'adagrad': 1, 'rmsprop': 1, 'adam': 2, 'adamw': 2,
               'lamb': 2}


def _opt_state_multiplier(program):
    """Optimizer moments per param byte, from the program's update
    ops (adam keeps 2 fp32 moments, momentum 1, sgd none)."""
    mult = 0
    for op in program.global_block().ops:
        if op.type in _OPT_STATES:
            mult = max(mult, _OPT_STATES[op.type])
    return mult


def _batch_tokens(program, feed_shapes):
    """(tokens, batch) of the largest batch feed: `tokens` is the
    leading-dims product (the compute / activation scale), `batch` is
    dim 0 — the ONLY dim the runner actually shards
    (_guard_local_batch), so candidate shardability is judged on it,
    not on the token product.  `feed_shapes` ({name: shape}) comes
    from the actual feed when the runner plans at first step; falls
    back to the program's declared feed shapes, where an unknown (-1)
    batch dim reads as batch 0 = 'assume divisible' (the
    transpile-time posture)."""
    toks, batch = 1, 0
    feed_shapes = feed_shapes or {}
    blk = program.global_block()
    names = set(feed_shapes)
    try:
        for op in blk.ops:
            if op.type == 'feed':
                names.update(op.output_arg_names)
    except Exception:
        pass
    for n in names:
        shape = feed_shapes.get(n)
        if shape is None:
            try:
                shape = tuple(getattr(blk.var(n), 'shape', ()) or ())
            except Exception:
                shape = ()
        if not shape:
            continue
        lead = [int(s) for s in shape[:-1]] or [int(shape[0])]
        t = int(np.prod([max(s, 1) for s in lead]))
        if t > toks:
            toks = t
            batch = max(0, int(shape[0]))
    return toks, batch


# ------------------------------------------------------ candidate layouts
def enumerate_layouts(ndev):
    """Every (dp, fsdp, tp) triple whose product is `ndev`,
    deterministically ordered dp-heaviest first (the tie-break the
    chooser inherits)."""
    ndev = max(1, int(ndev))
    out = []
    for dp in range(ndev, 0, -1):
        if ndev % dp:
            continue
        rest = ndev // dp
        for fsdp in range(rest, 0, -1):
            if rest % fsdp:
                continue
            out.append((dp, fsdp, rest // fsdp))
    return out


def _predict(kind, wire, model, unpriced):
    """Model-predicted seconds for `wire` bytes over `kind`, degrading
    to heuristic byte-count pricing (and counting the degradation)
    when comms_model.json is absent or has no entry — the planner
    never crashes on a missing model."""
    if wire <= 0:
        return 0.0
    pred = None
    try:
        from ..fluid import comms_plan as _cp
        pred = _cp.predict_seconds(kind, wire, model)
    except Exception:
        pred = None
    if pred is None:
        unpriced[0] += 1
        return _HEUR_LATENCY_S + wire / _HEUR_BW_BYTES_PER_S
    return float(pred)


def _effective_spec(name, shape, specs_by_name, hints, axis_sizes):
    """The spec a param will ACTUALLY execute under on a mesh with
    `axis_sizes`: a layer-stamped hint (program._sharding_hints, e.g.
    moe expert weights on 'ep') takes precedence when any of its axes
    survive this mesh, else the rule-matched spec — mirroring the
    runner's hint-first wrapper, so pricing and the HBM gate describe
    the shardings that really run."""
    h = hints.get(name) if hints else None
    if h is not None and len(tuple(h)) == len(tuple(shape)):
        sp = validate_spec(h, shape, axis_sizes)
        if sp is not None:
            return sp
    return validate_spec(specs_by_name.get(name), shape, axis_sizes)


def _shard_factor(spec, axis_sizes):
    f = 1
    if spec is None:
        return 1
    for entry in tuple(spec):
        if entry is None:
            continue
        for a in (entry if isinstance(entry, (tuple, list))
                  else (entry,)):
            f *= int(axis_sizes.get(a, 1))
    return f


def _price_layout(layout, inv, specs_by_name, tokens, batch, opt_mult,
                  act_residual, model, lay, hints=None, nproc=1):
    """One candidate's per-step cost estimate + per-device HBM
    residency.  Returns {'cost_s', 'comm_s', 'compute_s',
    'wire_bytes', 'hbm_bytes', 'unpriced'}."""
    from ..fluid import comms
    dp, fsdp, tp = layout
    ndev = dp * fsdp * tp
    axis_sizes = {lay.data_axis: dp, lay.fsdp_axis: fsdp,
                  lay.tp_axis: tp}
    batch_extent = max(1, dp * fsdp)
    # the runner shards ONLY the batch dim (dim 0), one per-process
    # slice of the data axes: judge shardability exactly as
    # _guard_local_batch will, on the batch dim — NOT on the token
    # product, which is divisible far more often and would price (and
    # HBM-admit) splits the execution silently replicates.  batch 0 =
    # unknown/dynamic (-1 declared dim): assume divisible.
    if nproc > 1:
        shardable = batch_extent % nproc == 0 and (
            batch <= 0 or batch % (batch_extent // nproc) == 0)
    else:
        shardable = batch <= 0 or batch % batch_extent == 0
    tok_dev = tokens / batch_extent if shardable else float(tokens)
    unpriced = [0]
    comm_s = wire_total = 0.0
    hbm = 0.0
    total_elems = 0
    for name, shape, nbytes, itemsize in inv:
        total_elems += nbytes // max(1, itemsize)
        spec = _effective_spec(name, shape, specs_by_name, hints,
                               axis_sizes)
        f = _shard_factor(spec, axis_sizes)
        shard_b = nbytes / f
        # residency: param + grad shards, moments over the update axis
        # (rule-sharded params carry their moments at the same factor;
        # replicated params' moments ride the ZeRO update_axis shard
        # when dim0 divides it — arXiv:2004.13336 through the
        # with_sharded_optimizer_states path)
        u = fsdp if fsdp > 1 else dp
        opt_f = f if f > 1 else (
            u if shape and shape[0] > 1 and shape[0] % u == 0 else 1)
        hbm += shard_b * 2.0 + opt_mult * nbytes / opt_f
        # gradient reduction over the replicated extent
        r = max(1, ndev // f)
        if r > 1:
            w = comms.wire_bytes('allreduce', shard_b, r)
            comm_s += _predict('allreduce', w, model, unpriced)
            wire_total += w
        if spec is not None:
            dim_axes = []
            axes_used = set()
            for entry in tuple(spec):
                axes = tuple((entry if isinstance(entry, (tuple, list))
                              else (entry,)) if entry else ())
                dim_axes.append(axes)
                axes_used.update(axes)
            if lay.fsdp_axis in axes_used and fsdp > 1:
                # fsdp scatter: gather the weight fwd+bwd, scatter the
                # grad back
                # each fsdp group gathers/scatters only ITS slice of
                # the other axes: the grad a tp-sharded weight
                # reduce-scatters is nbytes/tp (= shard_b * fsdp), not
                # the full tensor — pricing the full bytes would
                # penalize combined fsdp x tp layouts by tp x
                w_ag = comms.wire_bytes('allgather', shard_b, fsdp)
                w_rs = comms.wire_bytes('reducescatter',
                                        shard_b * fsdp, fsdp)
                comm_s += 2.0 * _predict('allgather', w_ag, model,
                                         unpriced)
                comm_s += _predict('reducescatter', w_rs, model,
                                   unpriced)
                wire_total += 2.0 * w_ag + w_rs
            if tp > 1 and len(shape) >= 2 and \
                    lay.tp_axis in axes_used:
                # tensor parallelism is never free on activations:
                # an input-dim (row-parallel / embedding-row) shard
                # allreduces the partial outputs, an output-dim
                # (column-parallel) shard allgathers them downstream
                # — tokens x out-columns bytes either way
                act_b = tok_dev * max(1, shape[-1]) * itemsize
                if lay.tp_axis in dim_axes[0]:
                    w_act = comms.wire_bytes('allreduce', act_b, tp)
                    comm_s += _predict('allreduce', w_act, model,
                                       unpriced)
                else:
                    w_act = comms.wire_bytes('allgather',
                                             act_b / tp, tp)
                    comm_s += _predict('allgather', w_act, model,
                                       unpriced)
                wire_total += w_act
    compute_s = 2.0 * total_elems * tok_dev * _FLOP_SECONDS
    hbm += act_residual / (batch_extent if shardable else 1) \
        + _ACT_BYTES_PER_TOKEN_FACTOR * tok_dev * 4.0
    return {'cost_s': comm_s + compute_s, 'comm_s': comm_s,
            'compute_s': compute_s, 'wire_bytes': wire_total,
            'hbm_bytes': hbm, 'unpriced': unpriced[0],
            'batch_shardable': shardable}


# --------------------------------------------------------------- the plan
class Plan(object):
    """One program's chosen layout: the (dp, fsdp, tp) mesh, the
    per-param PartitionSpecs, the batch axes, the weight-update
    sharding axis, and the priced-candidate table that justified it."""

    __slots__ = ('label', 'layout', 'specs', 'layout_axes',
                 'update_axis', 'batch_axes', 'candidates', 'chosen',
                 'rejected', '_digest')

    def __init__(self, label, layout, specs, lay, candidates,
                 chosen, rejected):
        self.label = label
        self.layout = layout            # (dp, fsdp, tp)
        self.specs = specs              # {param: PartitionSpec|None}
        self.layout_axes = lay
        dp, fsdp, tp = layout
        # execution-honest: when the batch dim cannot split over the
        # chosen dp x fsdp extent the runner replicates it
        # (_guard_local_batch), and the plan must say so — that is
        # what this layout was priced at
        self.batch_axes = tuple(
            a for a, s in ((lay.data_axis, dp), (lay.fsdp_axis, fsdp))
            if s > 1) if chosen.get('batch_shardable', True) else ()
        self.update_axis = lay.fsdp_axis if fsdp > 1 else (
            lay.data_axis if dp > 1 else None)
        self.candidates = candidates
        self.chosen = chosen
        self.rejected = rejected
        self._digest = None

    def param_rule(self, name, shape):
        """The runner's ``_param_sharding_rule`` form: the matched
        spec for sharded params, None for replicated ones — None (not
        P()) so the ZeRO accumulator wrapper still applies to
        replicated-param moments."""
        return self.specs.get(name)

    def mesh_sizes(self):
        dp, fsdp, tp = self.layout
        return {self.layout_axes.data_axis: dp,
                self.layout_axes.fsdp_axis: fsdp,
                self.layout_axes.tp_axis: tp}

    def build_mesh(self, devices=None):
        """A jax Mesh realizing the layout (size-1 axes dropped, like
        parallel.mesh.create_mesh; pure-replicated plans keep a
        1-axis dp mesh)."""
        import jax
        from jax.sharding import Mesh
        devices = devices if devices is not None else jax.devices()
        axes = [(a, s) for a, s in
                ((self.layout_axes.data_axis, self.layout[0]),
                 (self.layout_axes.fsdp_axis, self.layout[1]),
                 (self.layout_axes.tp_axis, self.layout[2]))
                if s > 1] or [(self.layout_axes.data_axis, 1)]
        shape = tuple(s for _, s in axes)
        arr = np.array(devices[:int(np.prod(shape))]).reshape(shape)
        return Mesh(arr, tuple(a for a, _ in axes))

    def digest(self):
        """Deterministic digest of everything the plan decided —
        folded (with the global digest()) into segment fingerprints so
        an executable can never be reused under a different plan."""
        if self._digest is None:
            spec_sig = ';'.join('%s=%s' % (n, self.specs[n])
                                for n in sorted(self.specs))
            raw = 'layout=%r,batch=%r,update=%r,%s' % (
                self.layout, self.batch_axes, self.update_axis,
                spec_sig)
            self._digest = 'auto_plan(%s)' % hashlib.sha256(
                raw.encode()).hexdigest()[:16]
        return self._digest

    def summary(self):
        dp, fsdp, tp = self.layout
        sharded = sorted(n for n, s in self.specs.items()
                         if s is not None)
        return {
            'layout': {'dp': dp, 'fsdp': fsdp, 'tp': tp},
            'batch_axes': list(self.batch_axes),
            'update_axis': self.update_axis,
            'digest': self.digest(),
            'params_sharded': len(sharded),
            'params_replicated': len(self.specs) - len(sharded),
            'sharded': [{'name': n, 'spec': str(self.specs[n])}
                        for n in sharded[:16]],
            'chosen': self.chosen,
            'candidates': self.candidates,
            'hbm_rejected': self.rejected,
        }


def _rules_signature(rules):
    sig = []
    for pat, spec in rules:
        tag = getattr(spec, '__name__', None) if callable(spec) \
            else str(spec)
        sig.append('%s->%s' % (pat, tag))
    return hashlib.sha256('|'.join(sig).encode()).hexdigest()[:12]


# digest() runs per step (plan_for's cache key): the default-rule
# signature is constant per process, and the model-content hash is
# keyed by the cached model OBJECT load_model returns (same object
# until the file changes; holding the ref keeps id() unambiguous)
_default_rules_sig = []
_model_hash_memo = {'model': None, 'hash': 'none'}


def _default_rules_signature():
    # idempotent memo: every racer computes the identical value, so a
    # double append is harmless ([0] is read) and a lock buys nothing
    if not _default_rules_sig:
        _default_rules_sig.append(            # staticcheck: unlocked
            _rules_signature(default_rules()))
    return _default_rules_sig[0]


def _model_content_hash(model):
    if model is None:
        return 'none'
    if model is _model_hash_memo['model']:
        return _model_hash_memo['hash']
    import json as _json
    h = hashlib.sha256(_json.dumps(
        model, sort_keys=True).encode()).hexdigest()[:8]
    # idempotent memo keyed by object identity: racers store the same
    # (model, hash) pair; torn interleavings only cost a re-hash
    _model_hash_memo['hash'] = h              # staticcheck: unlocked
    _model_hash_memo['model'] = model         # staticcheck: unlocked
    return h


def _budget_bytes(budget=None):
    """The HBM gate's budget: explicit arg, else the memviz plane's
    (FLAGS_memviz_budget_bytes or the device's reported limit); None
    disables the gate (CPU reports nothing)."""
    if budget is not None:
        return float(budget) or None
    try:
        from ..fluid import memviz
        return memviz.budget_bytes()
    except Exception:
        return None


def digest():
    """The GLOBAL auto-shard fingerprint component both runners fold
    into segment fingerprints (comms_plan.digest() pattern): flag off
    is a constant; on, it captures every plan input besides the
    program itself — the rule-set identity, the comms-model identity,
    and the power-of-two-bucketed HBM budget — so plans never go stale
    against cached executables and unchanged plans never retrace."""
    if not enabled():
        return 'auto_shard(off)'
    try:
        from ..fluid import comms_plan as _cp
        # hash the model CONTENTS (sort_keys makes it deterministic):
        # a recalibration that keeps the same collective names but new
        # alpha/beta values must change the fingerprint, or cached
        # executables would keep running a stale plan
        mid = _model_content_hash(_cp.load_model())
    except Exception:
        mid = 'none'
    budget = _budget_bytes()
    hb = 'off' if not budget else str(int(budget).bit_length())
    return 'auto_shard(on,rules=%s,model=%s,budget=%s)' % (
        _default_rules_signature(), mid, hb)


def build_plan(program, ndev=None, feed_shapes=None, budget=None,
               rules=None, layout=None, layouts=None, label=None):
    """Plan one program: match rules, enumerate + price + HBM-gate the
    candidate layouts, choose the cheapest admissible one.  Pure in
    (program, ndev, feed_shapes, flags, model file, budget); never
    raises on a missing/partial cost model (heuristic pricing,
    counted) and never returns None — with every candidate over
    budget the smallest-footprint one is kept (counted, reported), so
    training proceeds and the operator sees the squeeze."""
    t0 = time.perf_counter()
    if ndev is None:
        import jax
        ndev = len(jax.devices())
    lay = layout or SpecLayout()
    rules = rules if rules is not None else default_rules(lay)
    inv = _param_inventory(program)
    raw_specs = match_partition_rules(
        rules, [(n, s) for n, s, _b, _i in inv])
    small = {n for n, _s, b, _i in inv if b < MIN_SHARD_BYTES}
    raw_specs = {n: (None if n in small else sp)
                 for n, sp in raw_specs.items()}
    hints = getattr(program, '_sharding_hints', None) or {}
    tokens, batch = _batch_tokens(program, feed_shapes)
    opt_mult = _opt_state_multiplier(program)
    try:
        import jax
        nproc = jax.process_count()
    except Exception:
        nproc = 1
    try:
        from ..fluid import comms_plan as _cp
        model = _cp.load_model()
    except Exception:
        model = None
    # measured-peak calibration: a prior run's attribution row (any
    # layout) bounds the activation/temp residual the static param
    # terms miss
    act_residual = 0.0
    try:
        from ..fluid import memviz
        lbl = label or memviz.program_label(program)
        measured = memviz.peak_bytes(lbl)
        if measured:
            static_repl = sum(b * (2.0 + opt_mult)
                              for _n, _s, b, _i in inv)
            act_residual = max(0.0, float(measured) - static_repl)
    except Exception:
        lbl = label or 'program'
    budget = _budget_bytes(budget)
    cands = []
    rejected = 0
    unpriced_total = 0
    for lo in (layouts if layouts is not None
               else enumerate_layouts(ndev)):
        priced = _price_layout(lo, inv, raw_specs, tokens, batch,
                               opt_mult, act_residual, model, lay,
                               hints, nproc)
        unpriced_total += priced['unpriced']
        admissible = budget is None or priced['hbm_bytes'] <= budget
        if not admissible:
            rejected += 1
        cands.append(dict(priced, layout=list(lo),
                          admissible=admissible))
    pool = [c for c in cands if c['admissible']] or \
        sorted(cands, key=lambda c: c['hbm_bytes'])[:1]
    chosen = min(pool, key=lambda c: (c['cost_s'], -c['layout'][0]))
    lo = tuple(chosen['layout'])
    axis_sizes = {lay.data_axis: lo[0], lay.fsdp_axis: lo[1],
                  lay.tp_axis: lo[2]}
    specs = {n: _effective_spec(n, s, raw_specs, hints, axis_sizes)
             for n, s, _b, _i in inv}
    # legality first, pricing second (arXiv:2110.10548 discipline):
    # the chosen specs went through validate_spec, so a violation here
    # is a planner bug — fail with the var and class named BEFORE the
    # plan reaches a runner, never as a NamedSharding trace error
    from ..fluid import progcheck
    progcheck.check_sharding(
        {n: tuple(s) for n, s, _b, _i in inv}, specs,
        {a: sz for a, sz in axis_sizes.items() if int(sz) > 1},
        label=lbl, origin='auto_shard')
    plan = Plan(lbl, lo, specs, lay, cands, chosen, rejected)
    # observability: counters + gauges + the /statusz registry
    monitor.add('parallel/plan_builds')
    monitor.add('parallel/plan_candidates', float(len(cands)))
    if rejected:
        monitor.add('parallel/plan_hbm_rejected', float(rejected))
    if unpriced_total:
        # cost model absent/partial: the priced terms degraded to
        # heuristic byte-count pricing (PR-8 honesty convention)
        monitor.add('parallel/plan_unpriced', float(unpriced_total))
    monitor.add('parallel/plan_params_sharded',
                float(sum(1 for s in specs.values() if s is not None)))
    monitor.add('parallel/plan_params_replicated',
                float(sum(1 for s in specs.values() if s is None)))
    monitor.set_gauge('parallel/plan_layout_dp', float(lo[0]))
    monitor.set_gauge('parallel/plan_layout_fsdp', float(lo[1]))
    monitor.set_gauge('parallel/plan_layout_tp', float(lo[2]))
    monitor.observe('parallel/plan_seconds',
                    time.perf_counter() - t0)
    with _lock:
        if lbl not in _PLANS and len(_PLANS) >= _PLANS_CAP:
            _PLANS.pop(next(iter(_PLANS)))
        _PLANS[lbl] = plan.summary()
    return plan


# ----------------------------------------------------- runner integration
def plan_for(compiled, program, ndev=None, feed=None):
    """The run_parallel entry: build (or reuse) the auto plan for a
    CompiledProgram.  Cached on the compiled object for its LIFETIME —
    the chosen specs must be byte-stable across steps so the segment
    jit never retraces, and a live segment's executable memo keeps the
    plan it was traced with (the same contract every lowering flag and
    comms_plan follow): a budget/model/rules change applies to
    CompiledPrograms built AFTER the change, where digest() in the
    segment fingerprints guarantees the rebuilt program cannot reuse
    an executable traced under the old plan."""
    plan = getattr(compiled, '_auto_plan', None)
    if plan is not None:
        monitor.add('parallel/plan_reused')
        return plan
    feed_shapes = None
    if feed:
        feed_shapes = {n: tuple(np.shape(getattr(v, 'data', v)))
                       for n, v in feed.items()}
    plan = build_plan(program, ndev=ndev, feed_shapes=feed_shapes)
    compiled._auto_plan = plan
    return plan


def choose_mesh(compiled, program, feed=None, devices=None):
    """Mesh synthesis for an UNANNOTATED CompiledProgram (no
    with_mesh): build the plan over every device and realize its
    layout as the execution mesh.  Returns None when planning is
    disabled (the caller keeps the default 1-axis dp mesh)."""
    if not enabled():
        return None
    import jax
    devices = devices if devices is not None else jax.devices()
    plan = plan_for(compiled, program, ndev=len(devices), feed=feed)
    return plan.build_mesh(devices)


def transpile_plan(program, nranks):
    """The GradAllReduce transpiler's hook: the collective rewrite is
    rank-per-process data parallelism, so the layout space collapses
    to (nranks, 1, 1) — still priced, HBM-gated, registered and
    counted so a two-process job shows its plan on every rank."""
    if not enabled():
        return None
    return build_plan(program, ndev=nranks,
                      layouts=[(int(nranks), 1, 1)])


def report():
    """The /statusz ``auto_shard`` section: flag state, global digest,
    and the bounded per-program plan registry."""
    with _lock:
        plans = dict(_PLANS)
    return {
        'enabled': enabled(),
        'digest': digest(),
        'programs': plans,
        'counters': {
            k: monitor.counter_value('parallel/' + k)
            for k in ('plan_builds', 'plan_reused', 'plan_candidates',
                      'plan_hbm_rejected', 'plan_unpriced',
                      'plan_params_sharded',
                      'plan_params_replicated')},
        'layout': {
            'dp': monitor.gauge_value('parallel/plan_layout_dp'),
            'fsdp': monitor.gauge_value('parallel/plan_layout_fsdp'),
            'tp': monitor.gauge_value('parallel/plan_layout_tp')},
    }
