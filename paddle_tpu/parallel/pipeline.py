"""Pipeline parallelism: microbatch pipeline over a 'pp' mesh axis.

Reference: PipelineOptimizer (python/paddle/fluid/optimizer.py:3311) +
PipelineTrainer/SectionWorker threads passing Scopes through blocking
queues (framework/trainer.h:114, framework/pipeline_trainer.cc:26).

TPU-native re-design: no threads or queues — a GPipe schedule expressed
as a fori_loop where every device applies ITS stage (all stages' params
live on their own devices via shard_map) and activations hop stages with
ppermute.  jax.vjp through ppermute reverses the ring, so grads flow
back through the pipeline automatically — the reference's backward
section workers come for free from autodiff.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..compat import shard_map as _shard_map


def pipeline_apply_inner(stage_fn, params, x_micro, axis_name):
    """Inside shard_map.
    params: stage params, ALREADY stage-sharded (leading dim removed).
    x_micro: [n_micro, micro_B, ...] microbatches (replicated input).
    Returns [n_micro, micro_B, ...] outputs (replicated)."""
    n_stages = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    total = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    buf = jnp.zeros_like(x_micro[0])  # current activation on this device
    out = jnp.zeros_like(x_micro)

    def body(t, carry):
        buf, out = carry
        # stage 0 ingests microbatch t (if any remain)
        feed = x_micro[jnp.minimum(t, n_micro - 1)]
        buf = jnp.where(idx == 0, feed, buf)
        y = stage_fn(params, buf)
        # last stage emits microbatch t-(n_stages-1)
        mi = t - (n_stages - 1)
        emit = jnp.logical_and(idx == n_stages - 1, mi >= 0)
        out = jax.lax.cond(
            emit,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(mi, 0), 0),
            lambda o: o, out)
        # hop activations to the next stage
        buf = jax.lax.ppermute(y, axis_name, perm)
        return buf, out

    _, out = jax.lax.fori_loop(0, total, body, (buf, out))
    # broadcast the last stage's outputs to every device
    src = n_stages - 1
    mask = (idx == src).astype(out.dtype)
    return jax.lax.psum(out * mask, axis_name)


def pipeline_apply(stage_fn, stage_params, x, mesh, axis='pp',
                   n_microbatches=4):
    """stage_params: pytree with leading dim = n_stages (stacked per-stage
    params); x: [B, ...] global batch.  Activations must have the same
    shape across stages (classic GPipe restriction for the rotating
    buffer)."""
    n_stages = mesh.shape[axis]
    b = x.shape[0]
    assert b % n_microbatches == 0
    x_micro = x.reshape((n_microbatches, b // n_microbatches)
                        + x.shape[1:])

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)

    def inner(params, xm):
        # strip the per-stage leading dim of 1
        params = jax.tree.map(lambda p: p[0], params)
        return pipeline_apply_inner(stage_fn, params, xm, axis)

    f = _shard_map(inner, mesh=mesh,
                      in_specs=(param_specs, P()), out_specs=P())
    out = f(stage_params, x_micro)
    return out.reshape((b,) + out.shape[2:])
