"""Ring attention: context parallelism over a mesh axis.

NEW capability vs the reference (SURVEY.md §5: sequence scaling there is
LoD batching only).  The sequence dim is sharded over the 'sp' axis; K/V
blocks rotate around the ICI ring via ppermute while each device
accumulates its Q-block's attention with a numerically-stable online
softmax (flash-attention style streaming).  Communication overlaps with
the next block's compute (XLA schedules the ppermute DMA concurrently).

Differentiable: jax.vjp through ppermute reverses the ring, so the same
code serves training.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..compat import shard_map as _shard_map


def _block_attend(q, k, v, m, l, acc, q_off, k_off, scale, causal,
                  dropout_rate=0.0, dropout_seed=None,
                  dropout_g_offset=0):
    """One K/V block of online-softmax attention.
    q [B,Tq,H,D], k/v [B,Tk,H,D]; m,l [B,H,Tq]; acc [B,Tq,H,D].
    Dropout (post-softmax, reference semantics) draws the SAME counter
    hash as the flash kernels at GLOBAL (q_off/k_off-shifted)
    positions, so ring-sharded and dense runs are bit-identical
    stochastic functions of the seed; the normalizer l accumulates the
    undropped probs, so the lse merge stays exact."""
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    tq, tk = q.shape[1], k.shape[1]
    if causal:
        qpos = q_off + jnp.arange(tq)
        kpos = k_off + jnp.arange(tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (m_new == -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    if dropout_rate:
        from ..ops.pallas.flash_attention import dropout_keep_dense
        b, h = q.shape[0], q.shape[2]
        keep = dropout_keep_dense(dropout_seed, b, h, tq, tk, q_off,
                                  k_off, dropout_g_offset,
                                  dropout_rate)
        p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
    pv = jnp.einsum('bhqk,bkhd->bqhd', p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    acc_new = acc * jnp.transpose(corr, (0, 2, 1))[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention_inner(q, k, v, axis_name, causal=False,
                         dropout_rate=0.0, dropout_seed=None,
                         dropout_g_offset=0):
    """Call INSIDE shard_map with q,k,v sequence-sharded [B,T_loc,H,D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % n) for j in range(n)]

    m0 = jnp.full((b, h, tq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    acc0 = jnp.zeros((b, tq, h, d), jnp.float32)

    def body(i, carry):
        m, l, acc, kk, vv = carry
        kv_idx = (idx - i) % n
        m, l, acc = _block_attend(q, kk, vv, m, l, acc,
                                  idx * tq, kv_idx * tq, scale, causal,
                                  dropout_rate, dropout_seed,
                                  dropout_g_offset)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return m, l, acc, kk, vv

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body,
                                        (m0, l0, acc0, k, v))
    denom = jnp.transpose(jnp.maximum(l, 1e-20), (0, 2, 1))[..., None]
    return (acc / denom).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis='sp', causal=False):
    """q,k,v: GLOBAL [B,T,H,D] arrays; returns [B,T,H,D].  Shards T over
    `axis` and runs the ring."""
    spec = P(None, axis, None, None)
    f = _shard_map(
        functools.partial(ring_attention_inner, axis_name=axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)


def ring_flash_attention_inner(q, k, v, axis_name, causal=False,
                               dropout_rate=0.0, dropout_seed=None,
                               dropout_g_offset=0):
    """Ring attention with the Pallas FLASH kernel as the per-block
    engine: each hop runs blockwise flash attention over the resident
    K/V shard (no [T_loc, T_loc] scores in HBM — the long-context
    configuration this exists for), and partial results merge in
    log-sum-exp space:

        L' = logaddexp(L, lse_blk)
        o' = o * exp(L - L') + o_blk * exp(lse_blk - L')

    Differentiable end-to-end: the flash kernel exposes lse as a real
    output (ops/pallas/flash_attention.py _flash_lse) whose cotangent
    folds into dS inside the backward kernels, and jax.vjp reverses the
    ppermute ring.  Call INSIDE shard_map with q,k,v sequence-sharded
    [B, T_loc, H, D]."""
    from ..ops.pallas.flash_attention import flash_attention_with_lse
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    l0 = jnp.full((b, h, tq), -jnp.inf, jnp.float32)

    def _drop_kw(k_off):
        if not dropout_rate:
            return {}
        return {'dropout_rate': dropout_rate,
                'dropout_seed': dropout_seed,
                'dropout_offsets': (idx * tq, k_off),
                'dropout_g_offset': dropout_g_offset}

    def full_block(kk, vv, k_off):
        return flash_attention_with_lse(q, kk, vv, causal=False,
                                        **_drop_kw(k_off))

    def diag_block(kk, vv, k_off):
        return flash_attention_with_lse(q, kk, vv, causal=True,
                                        **_drop_kw(k_off))

    def skip_block(kk, vv, k_off):
        return (jnp.zeros((b, tq, h, d), q.dtype),
                jnp.full((b, h, tq), -jnp.inf, jnp.float32))

    def body(i, carry):
        o, lse, kk, vv = carry
        kv_idx = (idx - i) % n
        if causal:
            # kv block ahead of the diagonal contributes nothing;
            # on the diagonal the block is internally causal
            case = jnp.where(kv_idx > idx, 2,
                             jnp.where(kv_idx == idx, 1, 0))
            o_blk, lse_blk = jax.lax.switch(
                case, [full_block, diag_block, skip_block], kk, vv,
                kv_idx * tq)
        else:
            o_blk, lse_blk = full_block(kk, vv, kv_idx * tq)
        o_blk = o_blk.astype(jnp.float32)
        lse_new = jnp.logaddexp(lse, lse_blk)
        # guard rows no block has touched yet (-inf - -inf = nan)
        w_old = jnp.where(jnp.isfinite(lse),
                          jnp.exp(lse - lse_new), 0.0)
        w_blk = jnp.where(jnp.isfinite(lse_blk),
                          jnp.exp(lse_blk - lse_new), 0.0)
        # [B,H,T] weights -> [B,T,H,1] to scale outputs
        wo = jnp.transpose(w_old, (0, 2, 1))[..., None]
        wb = jnp.transpose(w_blk, (0, 2, 1))[..., None]
        o = o * wo + o_blk * wb
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return o, lse_new, kk, vv

    o, lse, _, _ = jax.lax.fori_loop(0, n, body, (o0, l0, k, v))
    return o.astype(q.dtype)


def ring_flash_attention(q, k, v, mesh, axis='sp', causal=False):
    """Global-array wrapper for ring_flash_attention_inner."""
    spec = P(None, axis, None, None)
    f = _shard_map(
        functools.partial(ring_flash_attention_inner, axis_name=axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)


def reference_attention(q, k, v, causal=False):
    """Dense reference for testing: [B,T,H,D]."""
    d = q.shape[-1]
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k) / (d ** 0.5)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)
