"""Ring attention: context parallelism over a mesh axis.

NEW capability vs the reference (SURVEY.md §5: sequence scaling there is
LoD batching only).  The sequence dim is sharded over the 'sp' axis; K/V
blocks rotate around the ICI ring via ppermute while each device
accumulates its Q-block's attention with a numerically-stable online
softmax (flash-attention style streaming).  Communication overlaps with
the next block's compute (XLA schedules the ppermute DMA concurrently).

Differentiable: jax.vjp through ppermute reverses the ring, so the same
code serves training.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _block_attend(q, k, v, m, l, acc, q_off, k_off, scale, causal):
    """One K/V block of online-softmax attention.
    q [B,Tq,H,D], k/v [B,Tk,H,D]; m,l [B,H,Tq]; acc [B,Tq,H,D]."""
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(tq)
        kpos = k_off + jnp.arange(tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (m_new == -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum('bhqk,bkhd->bqhd', p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    acc_new = acc * jnp.transpose(corr, (0, 2, 1))[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention_inner(q, k, v, axis_name, causal=False):
    """Call INSIDE shard_map with q,k,v sequence-sharded [B,T_loc,H,D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % n) for j in range(n)]

    m0 = jnp.full((b, h, tq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    acc0 = jnp.zeros((b, tq, h, d), jnp.float32)

    def body(i, carry):
        m, l, acc, kk, vv = carry
        kv_idx = (idx - i) % n
        m, l, acc = _block_attend(q, kk, vv, m, l, acc,
                                  idx * tq, kv_idx * tq, scale, causal)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return m, l, acc, kk, vv

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body,
                                        (m0, l0, acc0, k, v))
    denom = jnp.transpose(jnp.maximum(l, 1e-20), (0, 2, 1))[..., None]
    return (acc / denom).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis='sp', causal=False):
    """q,k,v: GLOBAL [B,T,H,D] arrays; returns [B,T,H,D].  Shards T over
    `axis` and runs the ring."""
    spec = P(None, axis, None, None)
    f = jax.shard_map(
        functools.partial(ring_attention_inner, axis_name=axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)


def reference_attention(q, k, v, causal=False):
    """Dense reference for testing: [B,T,H,D]."""
    d = q.shape[-1]
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k) / (d ** 0.5)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)
