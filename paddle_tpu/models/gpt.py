"""Decoder-only causal language model (GPT-2 style), built from the
same fluid layer surface as the other model families.

The reference era predates GPT as a shipped model, but its framework
contract — program + layers + executor — is exactly what a causal LM
needs; this family exists to exercise the long-context machinery
(causal Pallas flash attention, ring/sequence parallelism) as a model
users expect to find.  Blocks are pre-LN (x + attn(ln(x)),
x + mlp(ln(x))); attention is `bert.multi_head_attention(causal=True)`
so the seq >= flash_min_len dispatch, kernels, and masks are shared
with the encoder stack.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

from . import bert as _bert


class GptConfig(object):
    def __init__(self, vocab_size=50257, hidden=768, layers=12,
                 heads=12, intermediate=None, max_pos=1024,
                 dropout=0.1, attn_dropout=None, use_flash=True,
                 moe_experts=0, moe_hidden=None, moe_aux_weight=0.01,
                 moe_capacity_factor=2.0, moe_top_k=1,
                 use_context_parallel=False):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.heads = heads
        self.intermediate = intermediate or 4 * hidden
        self.max_pos = max_pos
        self.dropout = dropout
        self.attn_dropout = dropout if attn_dropout is None else \
            attn_dropout
        self.use_flash = use_flash
        self.flash_min_len = 512
        # MoE FFN blocks (GShard top-1, layers.moe): moe_experts > 0
        # swaps the dense MLP for an expert-parallel MoE that shards
        # over an 'ep' mesh axis under CompiledProgram.with_mesh
        self.moe_experts = moe_experts
        self.moe_hidden = moe_hidden or self.intermediate
        self.moe_aux_weight = moe_aux_weight
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_top_k = moe_top_k
        # route attention through layers.context_parallel_attention
        # (ring attention over the 'sp' axis on a mesh; dense fallback
        # on one device)
        self.use_context_parallel = use_context_parallel


BASE = GptConfig()
TINY = GptConfig(vocab_size=97, hidden=64, layers=2, heads=4,
                 max_pos=128, dropout=0.0)


def decoder_block(x, cfg, is_test, aux_losses=None):
    """Pre-LN GPT-2 block; with cfg.moe_experts the MLP is a GShard
    MoE FFN and its load-balance loss is appended to aux_losses."""
    a = layers.layer_norm(x, begin_norm_axis=2)
    a = _bert.multi_head_attention(a, None, cfg, is_test, causal=True)
    if not is_test and cfg.dropout:
        a = layers.dropout(a, cfg.dropout, is_test=is_test,
                           dropout_implementation='upscale_in_train')
    x = layers.elementwise_add(x, a)
    m = layers.layer_norm(x, begin_norm_axis=2)
    if cfg.moe_experts:
        m, aux = layers.moe(m, num_experts=cfg.moe_experts,
                            hidden_size=cfg.moe_hidden,
                            capacity_factor=cfg.moe_capacity_factor,
                            aux_weight=cfg.moe_aux_weight,
                            top_k=cfg.moe_top_k)
        if aux_losses is not None:
            aux_losses.append(aux)
    else:
        m = layers.fc(m, size=cfg.intermediate, num_flatten_dims=2,
                      act='gelu')
        m = layers.fc(m, size=cfg.hidden, num_flatten_dims=2)
    if not is_test and cfg.dropout:
        m = layers.dropout(m, cfg.dropout, is_test=is_test,
                           dropout_implementation='upscale_in_train')
    return layers.elementwise_add(x, m)


def gpt_decoder(ids, pos_ids, cfg, is_test=False, aux_losses=None):
    tok = layers.embedding(ids, size=[cfg.vocab_size, cfg.hidden],
                           param_attr=fluid.ParamAttr(name='gpt_wte'))
    pos = layers.embedding(pos_ids, size=[cfg.max_pos, cfg.hidden])
    x = layers.elementwise_add(tok, pos)
    if not is_test and cfg.dropout:
        x = layers.dropout(x, cfg.dropout, is_test=is_test,
                           dropout_implementation='upscale_in_train')
    for _ in range(cfg.layers):
        x = decoder_block(x, cfg, is_test, aux_losses=aux_losses)
    return layers.layer_norm(x, begin_norm_axis=2)


def build_lm(cfg=None, seq_len=128, is_test=False):
    """Next-token LM: feeds ids/pos/labels, returns (feeds, logits,
    loss).  labels are the inputs shifted left by the caller;
    ignore_index=-1 masks padding and the final position."""
    cfg = cfg or BASE
    ids = fluid.layers.data('ids', shape=[seq_len], dtype='int64')
    pos = fluid.layers.data('pos_ids', shape=[seq_len], dtype='int64')
    labels = fluid.layers.data('labels', shape=[seq_len], dtype='int64')
    aux_losses = []
    h = gpt_decoder(ids, pos, cfg, is_test, aux_losses=aux_losses)
    logits = layers.fc(h, size=cfg.vocab_size, num_flatten_dims=2)
    loss = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(labels, [2]), ignore_index=-1)
    loss = layers.mean(loss)
    if not is_test:
        # the load-balance term belongs in the TRAINING objective
        # only; eval loss stays the bare LM cross-entropy so
        # perplexities compare across dense/MoE models
        for aux in aux_losses:
            loss = layers.elementwise_add(loss, aux)
    feeds = {'ids': ids, 'pos_ids': pos, 'labels': labels}
    return feeds, logits, loss


def lm_batch(ids_2d):
    """[B, T] token batch -> feed dict with positions and shifted
    labels (last position ignored)."""
    ids_2d = np.asarray(ids_2d, 'int64')
    b, t = ids_2d.shape
    pos = np.tile(np.arange(t, dtype='int64'), (b, 1))
    labels = np.full((b, t), -1, 'int64')
    labels[:, :-1] = ids_2d[:, 1:]
    return {'ids': ids_2d, 'pos_ids': pos, 'labels': labels}


def greedy_generate(exe, infer_prog, logits_var, prompt, steps, cfg,
                    scope=None):
    """Host-driven greedy decoding: re-scores the growing prefix padded
    to max_pos each step (one executable total; the executor re-traces
    only if the padded length changes).  prompt: [T0] ints with
    T0 < cfg.max_pos.  Returns the full generated id list — possibly
    fewer than `steps` new tokens if the context fills max_pos first."""
    toks = list(int(t) for t in np.asarray(prompt).ravel())
    t_max = cfg.max_pos
    if len(toks) >= t_max:
        raise ValueError(
            'prompt length %d must be < cfg.max_pos (%d)'
            % (len(toks), t_max))
    for _ in range(steps):
        cur = len(toks)
        ids = np.zeros((1, t_max), 'int64')
        ids[0, :cur] = toks
        feed = {'ids': ids,
                'pos_ids': np.arange(t_max, dtype='int64')[None, :],
                'labels': np.full((1, t_max), -1, 'int64')}
        out, = exe.run(infer_prog, feed=feed,
                       fetch_list=[logits_var], scope=scope)
        nxt = int(np.asarray(out)[0, cur - 1].argmax())
        toks.append(nxt)
        if len(toks) >= t_max:
            break
    return toks


def synthetic_batch(cfg, batch, seq_len, rng):
    ids = rng.randint(0, cfg.vocab_size, (batch, seq_len))
    return lm_batch(ids)
