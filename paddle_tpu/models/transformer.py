"""Transformer NMT seq2seq (BASELINE.json config 4).

Reference workload: variable-length LoDTensor paths.  TPU-native
re-design: bucketed padding + explicit masks instead of LoD (see
SURVEY.md §5 long-context notes) — src/tgt are padded to the bucket
length and mask tensors drive attention and loss.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


class TransformerConfig(object):
    def __init__(self, src_vocab=10000, tgt_vocab=10000, d_model=512,
                 heads=8, ffn=2048, enc_layers=6, dec_layers=6,
                 dropout=0.1, max_len=256):
        self.src_vocab = src_vocab
        self.tgt_vocab = tgt_vocab
        self.d_model = d_model
        self.heads = heads
        self.ffn = ffn
        self.enc_layers = enc_layers
        self.dec_layers = dec_layers
        self.dropout = dropout
        self.max_len = max_len


BASE = TransformerConfig()
TINY = TransformerConfig(src_vocab=500, tgt_vocab=500, d_model=64,
                         heads=4, ffn=128, enc_layers=2, dec_layers=2,
                         max_len=64)


def _attention(q_in, kv_in, bias, cfg, is_test, cache=None):
    h, heads = cfg.d_model, cfg.heads
    d = h // heads
    q = layers.fc(q_in, size=h, num_flatten_dims=2)
    k = layers.fc(kv_in, size=h, num_flatten_dims=2)
    v = layers.fc(kv_in, size=h, num_flatten_dims=2)

    def to_heads(t):
        t = layers.reshape(t, [0, 0, heads, d])
        return layers.transpose(t, [0, 2, 1, 3])

    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    scores = layers.matmul(q, k, transpose_y=True, alpha=d ** -0.5)
    if bias is not None:
        scores = layers.elementwise_add(scores, bias)
    probs = layers.softmax(scores)
    if not is_test and cfg.dropout:
        probs = layers.dropout(probs, cfg.dropout, is_test=is_test,
                               dropout_implementation='upscale_in_train')
    ctx = layers.matmul(probs, v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, h])
    return layers.fc(ctx, size=h, num_flatten_dims=2)


def _ffn(x, cfg, is_test):
    out = layers.fc(x, size=cfg.ffn, num_flatten_dims=2, act='relu')
    if not is_test and cfg.dropout:
        out = layers.dropout(out, cfg.dropout, is_test=is_test,
                             dropout_implementation='upscale_in_train')
    return layers.fc(out, size=cfg.d_model, num_flatten_dims=2)


def _add_norm(x, sub):
    return layers.layer_norm(layers.elementwise_add(x, sub),
                             begin_norm_axis=2)


def _pos_encoding(seq_len, d_model):
    pos = np.arange(seq_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    pe = np.zeros((seq_len, d_model), np.float32)
    pe[:, 0::2] = np.sin(angle[:, 0::2])
    pe[:, 1::2] = np.cos(angle[:, 1::2])
    return pe


def _embed(ids, vocab, seq_len, cfg, is_test):
    from ..fluid.layer_helper import LayerHelper
    emb = layers.embedding(ids, size=[vocab, cfg.d_model])
    emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
    # trace-time position encoding: sized from the RUNTIME sequence
    # length, so one program serves every length bucket
    # (reader.BucketedGeneratorLoader) with one executable per bucket
    helper = LayerHelper('position_encoding')
    pe = helper.create_variable_for_type_inference(emb.dtype)
    pe.stop_gradient = True
    helper.append_op('position_encoding', inputs={'X': emb},
                     outputs={'Out': pe},
                     attrs={'d_model': cfg.d_model}, infer_shape=False)
    pe.shape = (1, seq_len, cfg.d_model)
    x = layers.elementwise_add(emb, pe)
    if not is_test and cfg.dropout:
        x = layers.dropout(x, cfg.dropout, is_test=is_test,
                           dropout_implementation='upscale_in_train')
    return x


def _pad_bias(mask):
    """[B,T] 1/0 mask -> additive [B,1,1,T]."""
    return layers.scale(
        layers.unsqueeze(layers.unsqueeze(mask, [1]), [1]),
        scale=10000.0, bias=-10000.0)


def _causal_bias(x, seq_len):
    """Additive causal bias sized from x's runtime length (bucketed
    batches re-trace per length; see _embed)."""
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper('causal_mask_like')
    b = helper.create_variable_for_type_inference(x.dtype)
    b.stop_gradient = True
    helper.append_op('causal_mask_like', inputs={'X': x},
                     outputs={'Out': b}, infer_shape=False)
    b.shape = (1, 1, seq_len, seq_len)
    return b


def encoder(src_ids, src_mask, seq_len, cfg, is_test=False):
    x = _embed(src_ids, cfg.src_vocab, seq_len, cfg, is_test)
    bias = _pad_bias(src_mask)
    for _ in range(cfg.enc_layers):
        x = _add_norm(x, _attention(x, x, bias, cfg, is_test))
        x = _add_norm(x, _ffn(x, cfg, is_test))
    return x, bias


def decoder(tgt_ids, enc_out, enc_bias, tgt_len, cfg, is_test=False):
    x = _embed(tgt_ids, cfg.tgt_vocab, tgt_len, cfg, is_test)
    self_bias = _causal_bias(x, tgt_len)
    for _ in range(cfg.dec_layers):
        x = _add_norm(x, _attention(x, x, self_bias, cfg, is_test))
        x = _add_norm(x, _attention(x, enc_out, enc_bias, cfg, is_test))
        x = _add_norm(x, _ffn(x, cfg, is_test))
    return layers.fc(x, size=cfg.tgt_vocab, num_flatten_dims=2)


def build(cfg=None, src_len=64, tgt_len=64, is_test=False,
          label_smooth_eps=0.1):
    cfg = cfg or BASE
    src = fluid.layers.data('src_ids', shape=[src_len], dtype='int64')
    src_mask = fluid.layers.data('src_mask', shape=[src_len],
                                 dtype='float32')
    tgt = fluid.layers.data('tgt_ids', shape=[tgt_len], dtype='int64')
    tgt_label = fluid.layers.data('tgt_label', shape=[tgt_len],
                                  dtype='int64')
    tgt_mask = fluid.layers.data('tgt_mask', shape=[tgt_len],
                                 dtype='float32')

    enc_out, enc_bias = encoder(src, src_mask, src_len, cfg, is_test)
    logits = decoder(tgt, enc_out, enc_bias, tgt_len, cfg, is_test)

    if label_smooth_eps:
        oh = layers.one_hot(tgt_label, depth=cfg.tgt_vocab)
        smooth = layers.label_smooth(oh, epsilon=label_smooth_eps)
        ce = layers.softmax_with_cross_entropy(logits, smooth,
                                               soft_label=True)
    else:
        ce = layers.softmax_with_cross_entropy(
            logits, layers.unsqueeze(tgt_label, [2]))
        ce = layers.squeeze(ce, [2])
    if len(ce.shape) == 3:
        ce = layers.squeeze(ce, [2]) if ce.shape[2] == 1 else \
            layers.reduce_sum(ce, dim=2)
    weighted = layers.elementwise_mul(ce, tgt_mask)
    denom = layers.reduce_sum(tgt_mask)
    loss = layers.elementwise_div(layers.reduce_sum(weighted), denom)
    feeds = {'src_ids': src, 'src_mask': src_mask, 'tgt_ids': tgt,
             'tgt_label': tgt_label, 'tgt_mask': tgt_mask}
    return feeds, logits, loss


def synthetic_batch(cfg, batch, src_len, tgt_len, rng):
    """Variable-length batch, bucket-padded (the LoD-replacement path)."""
    src_lens = rng.randint(src_len // 2, src_len + 1, batch)
    tgt_lens = rng.randint(tgt_len // 2, tgt_len + 1, batch)
    src = np.zeros((batch, src_len), 'int64')
    smask = np.zeros((batch, src_len), 'float32')
    tgt = np.zeros((batch, tgt_len), 'int64')
    tlabel = np.zeros((batch, tgt_len), 'int64')
    tmask = np.zeros((batch, tgt_len), 'float32')
    for b in range(batch):
        src[b, :src_lens[b]] = rng.randint(1, cfg.src_vocab,
                                           src_lens[b])
        smask[b, :src_lens[b]] = 1
        seq = rng.randint(1, cfg.tgt_vocab, tgt_lens[b] + 1)
        tgt[b, :tgt_lens[b]] = seq[:-1]
        tlabel[b, :tgt_lens[b]] = seq[1:]
        tmask[b, :tgt_lens[b]] = 1
    return {'src_ids': src, 'src_mask': smask, 'tgt_ids': tgt,
            'tgt_label': tlabel, 'tgt_mask': tmask}
