"""ResNet-50 for ImageNet (BASELINE.json configs 1: the north star).

Reference model definition style: the fluid image-classification model
used by reference distributed tests (dist_se_resnext.py and the classic
models repo ResNet): conv_bn blocks + bottleneck residuals, NCHW.
"""

import paddle_tpu.fluid as fluid

DEPTH_CFG = {
    18: ([2, 2, 2, 2], 'basic'),
    34: ([3, 4, 6, 3], 'basic'),
    50: ([3, 4, 6, 3], 'bottleneck'),
    101: ([3, 4, 23, 3], 'bottleneck'),
    152: ([3, 8, 36, 3], 'bottleneck'),
}


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False, data_format='NCHW'):
    conv = fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False, data_format=data_format)
    return fluid.layers.batch_norm(input=conv, act=act, is_test=is_test,
                                   data_layout=data_format)


def shortcut(input, ch_out, stride, is_test, data_format='NCHW'):
    ch_in = input.shape[1 if data_format == 'NCHW' else 3]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, is_test=is_test,
                             data_format=data_format)
    return input


def bottleneck_block(input, num_filters, stride, is_test,
                     data_format='NCHW'):
    conv0 = conv_bn_layer(input, num_filters, 1, act='relu',
                          is_test=is_test, data_format=data_format)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          act='relu', is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, act=None,
                          is_test=is_test, data_format=data_format)
    short = shortcut(input, num_filters * 4, stride, is_test,
                     data_format)
    return fluid.layers.elementwise_add(short, conv2, act='relu')


def basic_block(input, num_filters, stride, is_test,
                data_format='NCHW'):
    conv0 = conv_bn_layer(input, num_filters, 3, stride=stride,
                          act='relu', is_test=is_test,
                          data_format=data_format)
    conv1 = conv_bn_layer(conv0, num_filters, 3, act=None,
                          is_test=is_test, data_format=data_format)
    short = shortcut(input, num_filters, stride, is_test, data_format)
    return fluid.layers.elementwise_add(short, conv1, act='relu')


def resnet(input, class_dim=1000, depth=50, is_test=False,
           data_format='NCHW'):
    layers_cfg, block_type = DEPTH_CFG[depth]
    num_filters = [64, 128, 256, 512]
    conv = conv_bn_layer(input, 64, 7, stride=2, act='relu',
                         is_test=is_test, data_format=data_format)
    conv = fluid.layers.pool2d(conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type='max',
                               data_format=data_format)
    block_fn = bottleneck_block if block_type == 'bottleneck' \
        else basic_block
    for stage, count in enumerate(layers_cfg):
        for i in range(count):
            stride = 2 if i == 0 and stage != 0 else 1
            conv = block_fn(conv, num_filters[stage], stride, is_test,
                            data_format)
    pool = fluid.layers.pool2d(conv, pool_type='avg',
                               global_pooling=True, pool_size=1,
                               data_format=data_format)
    out = fluid.layers.fc(pool, size=class_dim)
    return out


def build(image_shape=(3, 224, 224), class_dim=1000, depth=50,
          is_test=False, data_format='NCHW'):
    if data_format == 'NHWC' and image_shape[0] in (1, 3):
        image_shape = (image_shape[1], image_shape[2], image_shape[0])
    img = fluid.layers.data('image', shape=list(image_shape),
                            dtype='float32')
    label = fluid.layers.data('label', shape=[1], dtype='int64')
    logits = resnet(img, class_dim, depth, is_test, data_format)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return {'image': img, 'label': label}, logits, loss, acc
