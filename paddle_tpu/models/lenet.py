"""LeNet-5 for MNIST (BASELINE.json config 0).

Reference model: python/paddle/fluid/tests/book/test_recognize_digits.py
(conv-pool x2 + fc-softmax).
"""

import paddle_tpu.fluid as fluid


def build(img=None, label=None):
    if img is None:
        img = fluid.layers.data('img', shape=[1, 28, 28], dtype='float32')
    if label is None:
        label = fluid.layers.data('label', shape=[1], dtype='int64')
    conv1 = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act='relu')
    conv2 = fluid.nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act='relu')
    prediction = fluid.layers.fc(input=conv2, size=10, act='softmax')
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return {'img': img, 'label': label}, prediction, loss, acc
