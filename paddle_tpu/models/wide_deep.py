"""Wide&Deep CTR model (BASELINE.json config 3).

Reference workload: embedding_lookup_sparse + SelectedRows sparse
gradients (operators/lookup_table_op with is_sparse=True).  TPU-native:
the embedding gradient is a dense scatter-add that XLA keeps on-chip;
the host-sharded embedding-table path for beyond-HBM vocabularies lives
in parallel/sparse_embedding.py.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


class WideDeepConfig(object):
    def __init__(self, sparse_feature_dim=1000, embedding_size=16,
                 num_sparse_fields=26, num_dense_fields=13,
                 hidden=(400, 400, 400)):
        self.sparse_feature_dim = sparse_feature_dim
        self.embedding_size = embedding_size
        self.num_sparse_fields = num_sparse_fields
        self.num_dense_fields = num_dense_fields
        self.hidden = hidden


BASE = WideDeepConfig()
TINY = WideDeepConfig(sparse_feature_dim=100, embedding_size=8,
                      num_sparse_fields=5, num_dense_fields=4,
                      hidden=(32, 16))


def build(cfg=None, is_sparse=True):
    cfg = cfg or BASE
    dense = fluid.layers.data('dense_input',
                              shape=[cfg.num_dense_fields],
                              dtype='float32')
    sparse = fluid.layers.data('sparse_input',
                               shape=[cfg.num_sparse_fields],
                               dtype='int64')
    label = fluid.layers.data('label', shape=[1], dtype='int64')

    # deep part: shared embedding table over all sparse fields
    emb = layers.embedding(
        sparse, size=[cfg.sparse_feature_dim, cfg.embedding_size],
        is_sparse=is_sparse,
        param_attr=fluid.ParamAttr(name='deep_embedding'))
    emb = layers.reshape(
        emb, [0, cfg.num_sparse_fields * cfg.embedding_size])
    deep = layers.concat([dense, emb], axis=1)
    for h in cfg.hidden:
        deep = layers.fc(deep, size=h, act='relu')

    # wide part: linear over one-hot sparse + dense
    wide_emb = layers.embedding(
        sparse, size=[cfg.sparse_feature_dim, 1], is_sparse=is_sparse,
        param_attr=fluid.ParamAttr(name='wide_embedding'))
    wide = layers.reduce_sum(wide_emb, dim=1)
    wide_dense = layers.fc(dense, size=1, bias_attr=False)

    logit = layers.fc(deep, size=1)
    logit = layers.elementwise_add(logit, wide)
    logit = layers.elementwise_add(logit, wide_dense)

    label_f = layers.cast(label, 'float32')
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label_f))
    prob = layers.sigmoid(logit)
    # [1-p, p] for AUC
    preds = layers.concat([layers.elementwise_sub(
        layers.ones_like(prob), prob), prob], axis=1)
    feeds = {'dense_input': dense, 'sparse_input': sparse,
             'label': label}
    return feeds, preds, loss


def synthetic_batch(cfg, batch, rng):
    dense = rng.rand(batch, cfg.num_dense_fields).astype('float32')
    sparse = rng.randint(0, cfg.sparse_feature_dim,
                         (batch, cfg.num_sparse_fields)).astype('int64')
    # label correlated with features so training shows progress
    score = dense.sum(1) + (sparse.sum(1) % 7) * 0.1
    label = (score > np.median(score)).astype('int64')[:, None]
    return {'dense_input': dense, 'sparse_input': sparse,
            'label': label}
