"""SE-ResNeXt for ImageNet.

The model the reference uses to exercise distributed training
(python/paddle/fluid/tests/unittests/dist_se_resnext.py) and
ParallelExecutor parity fixtures: ResNeXt grouped-conv bottlenecks
(cardinality splits) plus Squeeze-and-Excitation channel gating.
Written on the fluid layers API so the same script runs on the
reference framework.
"""

import paddle_tpu.fluid as fluid

DEPTH_CFG = {
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act, is_test=is_test)


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = fluid.layers.pool2d(input, pool_type='avg',
                               global_pooling=True)
    squeeze = fluid.layers.fc(pool, num_channels // reduction_ratio,
                              act='relu')
    excitation = fluid.layers.fc(squeeze, num_channels, act='sigmoid')
    return fluid.layers.elementwise_mul(input, excitation, axis=0)


def bottleneck_block(input, num_filters, stride, cardinality=32,
                     reduction_ratio=16, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act='relu',
                          is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act='relu', is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_test=is_test)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)

    ch_in = input.shape[1]
    if ch_in != num_filters * 2 or stride != 1:
        short = conv_bn_layer(input, num_filters * 2, 1, stride,
                              is_test=is_test)
    else:
        short = input
    return fluid.layers.elementwise_add(short, scale, act='relu')


def se_resnext(input, class_dim=1000, depth=50, cardinality=32,
               reduction_ratio=16, is_test=False,
               stage_filters=(128, 256, 512, 1024)):
    layers = DEPTH_CFG[depth]
    conv = conv_bn_layer(input, 64, 7, stride=2, act='relu',
                         is_test=is_test)
    conv = fluid.layers.pool2d(conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type='max')
    for stage, num_blocks in enumerate(layers):
        for i in range(num_blocks):
            conv = bottleneck_block(
                conv, stage_filters[stage],
                stride=2 if i == 0 and stage != 0 else 1,
                cardinality=cardinality,
                reduction_ratio=reduction_ratio, is_test=is_test)
    pool = fluid.layers.pool2d(conv, pool_type='avg', global_pooling=True)
    drop = fluid.layers.dropout(pool, dropout_prob=0.5, is_test=is_test)
    return fluid.layers.fc(drop, class_dim, act='softmax')


def build(image_shape=(3, 224, 224), class_dim=1000, depth=50,
          cardinality=32, reduction_ratio=16, is_test=False,
          stage_filters=(128, 256, 512, 1024)):
    """Feeds + softmax output + avg CE loss + accuracy (the shape the
    reference dist tests train)."""
    img = fluid.layers.data('image', shape=list(image_shape),
                            dtype='float32')
    label = fluid.layers.data('label', shape=[1], dtype='int64')
    out = se_resnext(img, class_dim, depth, cardinality, reduction_ratio,
                     is_test, stage_filters)
    cost = fluid.layers.cross_entropy(input=out, label=label)
    loss = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=out, label=label)
    return [img, label], out, loss, acc
