"""BERT-base encoder for pretraining (BASELINE.json config 2).

Reference workload: fused_attention + layer_norm + adam on the reference's
multihead_matmul fused op (operators/fused/multihead_matmul_op.*).  Built
here with fluid layers; XLA fuses the attention chain, and the pallas
flash-attention kernel (ops/pallas/) replaces the naive chain when
enabled via attrs['__flash__'].
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


class BertConfig(object):
    def __init__(self, vocab_size=30522, hidden=768, layers=12, heads=12,
                 intermediate=3072, max_pos=512, type_vocab=2,
                 dropout=0.1, attn_dropout=None, use_flash=True):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.heads = heads
        self.intermediate = intermediate
        self.max_pos = max_pos
        self.type_vocab = type_vocab
        self.dropout = dropout
        # dropout on the attention probabilities (reference default:
        # dropout inside attention) — runs IN the flash kernels via a
        # counter-hash mask, so the flash path takes it natively
        self.attn_dropout = dropout if attn_dropout is None \
            else attn_dropout
        self.use_flash = use_flash
        # measured on one v5e-class chip (BENCHMARKS.md): the batched
        # round-3 tuned kernels (bf16 MXU dots, 512/1024 blocks —
        # tools/bench_flash.py): flash beats the naive XLA chain from
        # seq 512 up (512: 6.3 vs 8.2 ms; 1024: 11.3 vs 21.5;
        # 2048: 36.7 vs 73.8 fwd+bwd) and only loses in the 256
        # pocket where XLA's fused chain fits VMEM outright
        self.flash_min_len = 512


BASE = BertConfig()
TINY = BertConfig(vocab_size=1000, hidden=64, layers=2, heads=4,
                  intermediate=128, max_pos=128)


def multi_head_attention(x, attn_bias, cfg, is_test, key_bias=None,
                         causal=False):
    """Self-attention: fused QKV projection -> scaled dot product ->
    output projection.  When the config allows it (no attention-probs
    dropout needed) the scaled-dot-product chain runs as ONE Pallas
    flash-attention kernel fwd+bwd (ops/pallas/flash_attention.py) —
    the reference's multihead_matmul fusion
    (operators/fused/multihead_matmul_op.cu), TPU-style.  causal=True
    masks future positions (decoder-only LMs): the flash kernel takes
    it natively, the naive chain adds a causal_mask_like bias."""
    h, heads = cfg.hidden, cfg.heads
    d = h // heads
    qkv = layers.fc(x, size=3 * h, num_flatten_dims=2)
    q, k, v = layers.split(qkv, 3, dim=2)

    if getattr(cfg, 'use_context_parallel', False):
        # sequence/context parallelism: the ring_attention op shards T
        # over the 'sp' mesh axis under CompiledProgram.with_mesh and
        # runs the ppermute K/V ring (dense fallback on one device).
        # The ring carries no attention bias yet — masked BERT inputs
        # must keep the standard path.
        if attn_bias is not None or key_bias is not None:
            raise ValueError(
                'use_context_parallel does not support attention '
                'masks/biases yet: drop the input mask or disable '
                'context parallelism')
        seq = x.shape[1]
        t_dim = seq if seq and seq > 0 else -1
        q3 = layers.reshape(q, [-1, t_dim, heads, d] if t_dim > 0
                            else [0, 0, heads, d])
        k3 = layers.reshape(k, [-1, t_dim, heads, d] if t_dim > 0
                            else [0, 0, heads, d])
        v3 = layers.reshape(v, [-1, t_dim, heads, d] if t_dim > 0
                            else [0, 0, heads, d])
        cp_drop = 0.0 if is_test else float(
            getattr(cfg, 'attn_dropout', cfg.dropout) or 0.0)
        out = layers.context_parallel_attention(
            q3, k3, v3, causal=causal,
            use_flash=getattr(cfg, 'cp_use_flash', False),
            axis=getattr(cfg, 'cp_axis', 'sp'),
            dropout_rate=cp_drop)
        ctx = layers.reshape(out, [0, 0, h])
        return layers.fc(ctx, size=h, num_flatten_dims=2)

    seq_len = x.shape[1] if len(x.shape) >= 2 else 0
    use_flash = getattr(cfg, 'use_flash', False) and \
        (seq_len is None or seq_len < 0 or
         seq_len >= getattr(cfg, 'flash_min_len', 1024)) and \
        (attn_bias is None or key_bias is not None)
    # the flash kernel consumes the [B, T] key_bias form only: with a
    # general attn_bias and no key_bias we must keep the naive chain
    # rather than silently dropping the mask.  Attention-prob dropout
    # (the reference BERT default) runs INSIDE the kernels since round
    # 5 — no [T, T] probs ever materialize.
    if use_flash:
        from ..fluid.layer_helper import LayerHelper

        def to_bthd(t):
            return layers.reshape(t, [0, 0, heads, d])

        q3, k3, v3 = to_bthd(q), to_bthd(k), to_bthd(v)
        helper = LayerHelper('fused_multihead_attention')
        out = helper.create_variable_for_type_inference(x.dtype)
        inputs = {'Q': q3, 'K': k3, 'V': v3}
        if key_bias is not None:
            inputs['KeyBias'] = key_bias
        adrop = 0.0 if is_test else float(
            getattr(cfg, 'attn_dropout', cfg.dropout) or 0.0)
        helper.append_op('fused_multihead_attention', inputs=inputs,
                         outputs={'Out': out},
                         attrs={'causal': bool(causal),
                                'dropout_rate': adrop},
                         infer_shape=False)
        out.shape = tuple(q3.shape)
        ctx = layers.reshape(out, [0, 0, h])
        return layers.fc(ctx, size=h, num_flatten_dims=2)

    def to_heads(t):
        t = layers.reshape(t, [0, 0, heads, d])
        return layers.transpose(t, [0, 2, 1, 3])

    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    scores = layers.matmul(q, k, transpose_y=True, alpha=d ** -0.5)
    if causal:
        from .transformer import _causal_bias
        scores = layers.elementwise_add(
            scores, _causal_bias(x, x.shape[1] or -1))
    if attn_bias is not None:
        scores = layers.elementwise_add(scores, attn_bias)
    probs = layers.softmax(scores)
    if not is_test and getattr(cfg, 'attn_dropout', cfg.dropout):
        probs = layers.dropout(probs,
                               getattr(cfg, 'attn_dropout', cfg.dropout),
                               is_test=is_test,
                               dropout_implementation='upscale_in_train')
    ctx = layers.matmul(probs, v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, h])
    return layers.fc(ctx, size=h, num_flatten_dims=2)


def encoder_layer(x, attn_bias, cfg, is_test, key_bias=None):
    attn = multi_head_attention(x, attn_bias, cfg, is_test,
                                key_bias=key_bias)
    if not is_test and cfg.dropout:
        attn = layers.dropout(attn, cfg.dropout, is_test=is_test,
                              dropout_implementation='upscale_in_train')
    x = layers.layer_norm(layers.elementwise_add(x, attn),
                          begin_norm_axis=2)
    ffn = layers.fc(x, size=cfg.intermediate, num_flatten_dims=2,
                    act='gelu')
    ffn = layers.fc(ffn, size=cfg.hidden, num_flatten_dims=2)
    if not is_test and cfg.dropout:
        ffn = layers.dropout(ffn, cfg.dropout, is_test=is_test,
                             dropout_implementation='upscale_in_train')
    return layers.layer_norm(layers.elementwise_add(x, ffn),
                             begin_norm_axis=2)


def bert_encoder(src_ids, pos_ids, sent_ids, input_mask, cfg,
                 is_test=False):
    emb = layers.embedding(src_ids, size=[cfg.vocab_size, cfg.hidden])
    pos = layers.embedding(pos_ids, size=[cfg.max_pos, cfg.hidden])
    sent = layers.embedding(sent_ids, size=[cfg.type_vocab, cfg.hidden])
    x = layers.elementwise_add(layers.elementwise_add(emb, pos), sent)
    x = layers.layer_norm(x, begin_norm_axis=2)
    if not is_test and cfg.dropout:
        x = layers.dropout(x, cfg.dropout, is_test=is_test,
                           dropout_implementation='upscale_in_train')
    # [B, T] mask -> additive bias: 0 where attended, -10000 where
    # padded.  The flash path consumes the [B, T] form directly; the
    # naive chain broadcasts the [B, 1, 1, T] form over heads/rows.
    key_bias = layers.scale(input_mask, scale=10000.0, bias=-10000.0)
    bias = layers.unsqueeze(layers.unsqueeze(key_bias, [1]), [1])
    for _ in range(cfg.layers):
        x = encoder_layer(x, bias, cfg, is_test, key_bias=key_bias)
    return x


def build_pretrain(cfg=None, seq_len=128, is_test=False):
    """Masked-LM + next-sentence pretraining heads (reference BERT
    pretraining workload)."""
    cfg = cfg or BASE
    src = fluid.layers.data('src_ids', shape=[seq_len], dtype='int64')
    pos = fluid.layers.data('pos_ids', shape=[seq_len], dtype='int64')
    sent = fluid.layers.data('sent_ids', shape=[seq_len], dtype='int64')
    mask = fluid.layers.data('input_mask', shape=[seq_len],
                             dtype='float32')
    mlm_label = fluid.layers.data('mlm_label', shape=[seq_len],
                                  dtype='int64')
    nsp_label = fluid.layers.data('nsp_label', shape=[1], dtype='int64')

    enc = bert_encoder(src, pos, sent, mask, cfg, is_test)
    # MLM head over all positions (dense path; gather of masked positions
    # is a host-side optimization)
    mlm_logits = layers.fc(enc, size=cfg.vocab_size, num_flatten_dims=2)
    mlm_loss = layers.softmax_with_cross_entropy(
        mlm_logits, layers.unsqueeze(mlm_label, [2]), ignore_index=-1)
    mlm_loss = layers.mean(mlm_loss)
    # NSP head on [CLS] (position 0)
    cls = layers.slice(enc, axes=[1], starts=[0], ends=[1])
    cls = layers.reshape(cls, [0, cfg.hidden])
    nsp_logits = layers.fc(cls, size=2)
    nsp_loss = layers.mean(
        layers.softmax_with_cross_entropy(nsp_logits, nsp_label))
    loss = layers.elementwise_add(mlm_loss, nsp_loss)
    feeds = {'src_ids': src, 'pos_ids': pos, 'sent_ids': sent,
             'input_mask': mask, 'mlm_label': mlm_label,
             'nsp_label': nsp_label}
    return feeds, enc, loss


def synthetic_batch(cfg, batch, seq_len, rng):
    src = rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype('int64')
    pos = np.tile(np.arange(seq_len), (batch, 1)).astype('int64')
    sent = np.zeros((batch, seq_len), 'int64')
    mask = np.ones((batch, seq_len), 'float32')
    mlm = np.where(rng.rand(batch, seq_len) < 0.15,
                   rng.randint(0, cfg.vocab_size, (batch, seq_len)),
                   -1).astype('int64')
    nsp = rng.randint(0, 2, (batch, 1)).astype('int64')
    return {'src_ids': src, 'pos_ids': pos, 'sent_ids': sent,
            'input_mask': mask, 'mlm_label': mlm, 'nsp_label': nsp}
