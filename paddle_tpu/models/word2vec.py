"""word2vec CBOW (reference book test:
python/paddle/fluid/tests/book/test_word2vec.py — embedding concat +
fc softmax over N-gram context).
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

EMB_SIZE = 32
N_GRAM = 4


def build(vocab_size=2000, emb_size=EMB_SIZE):
    words = [fluid.layers.data('word_%d' % i, shape=[1], dtype='int64')
             for i in range(N_GRAM)]
    target = fluid.layers.data('target', shape=[1], dtype='int64')
    embs = []
    for i, w in enumerate(words):
        e = layers.embedding(
            w, size=[vocab_size, emb_size],
            param_attr=fluid.ParamAttr(name='shared_w'))
        embs.append(layers.reshape(e, [0, emb_size]))
    concat = layers.concat(embs, axis=1)
    hidden = layers.fc(concat, size=256, act='sigmoid')
    pred = layers.fc(hidden, size=vocab_size, act='softmax')
    loss = layers.mean(layers.cross_entropy(pred, target))
    feeds = {w.name: w for w in words}
    feeds['target'] = target
    return feeds, pred, loss


def synthetic_batch(vocab_size, batch, rng):
    ctx = rng.randint(0, vocab_size, (batch, N_GRAM)).astype('int64')
    target = ((ctx.sum(1) + 1) % vocab_size).astype('int64')[:, None]
    out = {'word_%d' % i: ctx[:, i:i + 1] for i in range(N_GRAM)}
    out['target'] = target
    return out
