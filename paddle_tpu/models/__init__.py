"""Model zoo matching BASELINE.json configs:
LeNet (MNIST), ResNet-50 (ImageNet), BERT-base, Transformer NMT,
Wide&Deep CTR, word2vec — all built on the fluid layers API so they run
unchanged on the reference framework.
"""

from . import lenet
from . import resnet
from . import se_resnext
from . import bert
from . import gpt
from . import transformer
from . import wide_deep
from . import word2vec
