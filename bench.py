"""Benchmark: ResNet-50 ImageNet training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

vs_baseline compares against 365 images/sec/GPU — the per-chip throughput
of the reference's V100 ParallelExecutor ResNet-50 path in the fluid-v1.6
era (the reference repo itself publishes no numbers; see BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

# process birth, as close as a module can observe it: --cold children
# measure start->first-step from here (python+import cost included —
# that IS part of a service replica's restart latency)
_PROC_T0 = time.time()


def _enable_compile_cache():
    import jax
    cache_dir = os.environ.get('PADDLE_TPU_JAX_CACHE',
                               '/root/repo/.jax_cache')
    try:
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          1.0)
    except Exception:
        pass


def bench_resnet50(batch=128, steps=30, warmup=5, amp=True,
                   data_format='NHWC'):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, logits, loss, acc = models.resnet.build(
            data_format=data_format)
        opt = fluid.optimizer.Momentum(0.1, momentum=0.9)
        if amp:
            opt = fluid.contrib.mixed_precision.decorate(
                opt, use_dynamic_loss_scaling=True)
        opt.minimize(loss)

    rng = np.random.RandomState(0)
    import jax
    shape = (batch, 224, 224, 3) if data_format == 'NHWC' else \
        (batch, 3, 224, 224)
    # synthetic batch resident on device: measure compute, not the
    # host->device pipe (the input pipeline is benched separately)
    x = jax.device_put(rng.rand(*shape).astype('float32'))
    y = jax.device_put(rng.randint(0, 1000, (batch, 1)).astype('int32'))

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        # warm up BOTH call signatures used below (fetch vs no-fetch
        # compile to different XLA programs) so no compile lands in the
        # timed region
        for _ in range(warmup):
            exe.run(main, feed={'image': x, 'label': y}, fetch_list=[])
        l, = exe.run(main, feed={'image': x, 'label': y},
                     fetch_list=[loss])
        np.asarray(l)  # force completion of warmup before timing
        t0 = time.time()
        # steady-state steps: no per-step fetch, dispatch stays async
        for _ in range(steps - 1):
            exe.run(main, feed={'image': x, 'label': y}, fetch_list=[])
        last, = exe.run(main, feed={'image': x, 'label': y},
                        fetch_list=[loss])
        np.asarray(last)  # block on the last step
        dt = time.time() - t0
        global LAST_PERF
        try:
            cost = exe.program_cost(main, {'image': x, 'label': y},
                                    fetch_list=[loss])
            LAST_PERF = _perf_fields(dt / steps, cost)
        except Exception:
            LAST_PERF = {}
    return batch * steps / dt


# tools/profile_step.py sets this so the device trace covers ONLY the
# steady-state timed loop: wrapping warmup/compile floods the trace
# buffer with host events (1M cap) and the device plane gets dropped
TRACE_LOGDIR = None


def _chip_peak():
    """(peak bf16 TFLOP/s, peak HBM GB/s) for the attached chip kind.
    PADDLE_TPU_PEAK_TFLOPS / PADDLE_TPU_PEAK_HBM_GBPS override the
    builtin table unconditionally (differently-binned parts, new
    chips)."""
    import jax
    env_tf = os.environ.get('PADDLE_TPU_PEAK_TFLOPS')
    env_bw = os.environ.get('PADDLE_TPU_PEAK_HBM_GBPS')
    kind = jax.devices()[0].device_kind.lower()
    table = {'v5 lite': (197.0, 819.0), 'v5e': (197.0, 819.0),
             'v5p': (459.0, 2765.0), 'v4': (275.0, 1228.0),
             'v6': (918.0, 1640.0)}
    tf, bw = 197.0, 819.0
    for key, peaks in table.items():
        if key in kind:
            tf, bw = peaks
            break
    if env_tf:
        tf = float(env_tf)
    if env_bw:
        bw = float(env_bw)
    return tf, bw


# set by _timed_steps from XLA's own cost analysis of the program it
# just timed; benches merge it into their JSON line so every entry
# reports achieved TFLOP/s and MFU (round-4 VERDICT item 2)
LAST_PERF = {}

# set by _timed_steps from fluid.trace's flight recorder over the timed
# window: the per-step phase breakdown (bind / feed_h2d / dispatch /
# state_release / fetch_d2h ms) + wall percentiles, so a BENCH file
# EXPLAINS a regression (which phase grew) instead of just reporting it
LAST_PHASES = {}


def _step_phase_fields():
    return {'step_phases': LAST_PHASES} if LAST_PHASES else {}


def _monitor_fields():
    """Always-on runtime-stats subset recorded alongside throughput, so
    BENCH_*.json carries the counters (segment-cache behavior, compile
    seconds, bytes fed) next to every images/sec number.  Each --all
    entry runs in its own child process, so the registry is per-entry:
    these are the counts for THIS bench's runs (warmup included)."""
    try:
        from paddle_tpu.fluid import monitor
        hist = monitor.histogram_value(
            'executor/segment_compile_seconds') or {}
        run = monitor.histogram_value('executor/run_seconds') or {}
        bind = monitor.histogram_value('executor/bind_seconds') or {}
        return {'monitor': {
            'segment_cache_hit':
                monitor.counter_value('executor/segment_cache_hit'),
            'segment_cache_miss':
                monitor.counter_value('executor/segment_cache_miss'),
            'compile_seconds': round(hist.get('sum', 0.0), 3),
            'feed_bytes': monitor.counter_value('executor/feed_bytes'),
            # dispatch-side host accounting (steady-state fast path)
            'run_seconds': round(run.get('sum', 0.0), 4),
            'run_calls': run.get('count', 0),
            'fastpath_hits':
                monitor.counter_value('executor/fastpath_hits'),
            'scope_lookups':
                monitor.counter_value('executor/scope_lookups'),
            'bind_seconds': round(bind.get('sum', 0.0), 5),
            'h2d_bytes_async':
                monitor.counter_value('executor/h2d_bytes_async'),
        }}
    except Exception:
        return {}


def _flatten_metrics(rec, prefix='', out=None):
    """Numeric leaves of one bench record as dotted-path series names
    ('value', 'monitor.run_seconds', 'step_phases.dispatch_ms') — the
    per-series form BENCH_history.jsonl keeps and
    tools/check_regress.py gates on.  Bools and strings are not
    metrics; lists are positional noise and skipped."""
    out = {} if out is None else out
    if isinstance(rec, dict):
        for k, v in rec.items():
            _flatten_metrics(v, prefix + '%s.' % k, out)
    elif isinstance(rec, bool):
        pass
    elif isinstance(rec, (int, float)):
        out[prefix[:-1]] = float(rec)
    return out


def append_history(entry, rec, path=None):
    """Run-to-run regression substrate: every bench entry appends ONE
    JSON line (wall time, entry name, flattened numeric metrics) to
    BENCH_history.jsonl — the recorded trajectory
    tools/check_regress.py compares a fresh run against, so a
    regression between runs is a named CI failure instead of a human
    diffing BENCH_*.json by hand.  PADDLE_TPU_BENCH_HISTORY overrides
    the path (the regression gate's self-test isolates there);
    PADDLE_TPU_BENCH_RUN_ID groups lines from one sweep.  Never
    raises — history must not cost a bench its result."""
    try:
        if path is None:
            path = os.environ.get('PADDLE_TPU_BENCH_HISTORY') or \
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'BENCH_history.jsonl')
        line = {'ts': round(time.time(), 3), 'entry': str(entry),
                'run_id': os.environ.get('PADDLE_TPU_BENCH_RUN_ID'),
                'metrics': _flatten_metrics(rec)}
        with open(path, 'a') as f:
            f.write(json.dumps(line, sort_keys=True) + '\n')
        return path
    except Exception:
        return None


def _perf_fields(step_s, cost):
    if not cost or not cost.get('flops'):
        return {}
    peak_tf, peak_bw = _chip_peak()
    tflops = cost['flops'] / step_s / 1e12
    gbps = cost.get('bytes', 0.0) / step_s / 1e9
    return {'tflops': round(tflops, 2),
            'mfu_pct': round(100.0 * tflops / peak_tf, 2),
            'hbm_gbps': round(gbps, 1),
            'hbm_pct': round(100.0 * gbps / peak_bw, 1)}


class _wpg(object):
    """Scoped FLAGS_whole_program_grad=True for the transformer bench
    entries (one jax.vjp over the forward region instead of per-op
    grad replay — measured 10% on the s2048 flash path and never
    worse, BENCHMARKS.md round 4).  Restores the flag on exit so a
    same-process caller's programs keep the default per-op path."""

    def __enter__(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid.flags import get_flag
        self._prev = bool(get_flag('FLAGS_whole_program_grad'))
        fluid.set_flags({'FLAGS_whole_program_grad': True})

    def __exit__(self, *exc):
        import paddle_tpu.fluid as fluid
        fluid.set_flags({'FLAGS_whole_program_grad': self._prev})


def _timed_steps(exe, main_prog, feed, loss, steps=20, warmup=3):
    # device-resident feeds: measure compute, not the host->device
    # transfer (the chip is remote-attached, so per-step feeds would
    # dominate small models)
    import jax
    from paddle_tpu.fluid import trace as pt_trace
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    for _ in range(warmup):
        exe.run(main_prog, feed=feed, fetch_list=[])
    l, = exe.run(main_prog, feed=feed, fetch_list=[loss])
    np.asarray(l)
    if TRACE_LOGDIR:
        jax.profiler.start_trace(TRACE_LOGDIR)
    # flight recorder over the timed window only (a few us/step): the
    # entry's JSON then carries the step-phase breakdown.  An ALREADY
    # enabled tracer (FLAGS_trace=1 posture) keeps its own ring size —
    # resizing it would silently discard the user's retained steps
    trace_was_on = pt_trace.is_active()
    if not trace_was_on:
        pt_trace.enable(buffer_steps=steps)
    try:
        t0 = time.time()
        for _ in range(steps - 1):
            exe.run(main_prog, feed=feed, fetch_list=[])
        last, = exe.run(main_prog, feed=feed, fetch_list=[loss])
        np.asarray(last)
        dt = time.time() - t0
    finally:
        if TRACE_LOGDIR:
            jax.profiler.stop_trace()
        global LAST_PHASES
        try:
            roll = pt_trace.step_report(last=steps)['rollup']
            LAST_PHASES = {
                'wall_p50_ms': round(roll['wall_p50_ms'], 3),
                'wall_p99_ms': round(roll['wall_p99_ms'], 3),
                'phases_ms_per_step': {
                    n: round(v / max(roll['count'], 1), 3)
                    for n, v in roll['phases_ms'].items()},
            }
        except Exception:
            LAST_PHASES = {}
        if not trace_was_on:
            pt_trace.disable()
            pt_trace.reset()
    global LAST_PERF
    try:
        cost = exe.program_cost(main_prog, feed, fetch_list=[loss])
        LAST_PERF = _perf_fields(dt / steps, cost)
    except Exception as e:
        LAST_PERF = {}
        sys.stderr.write('cost analysis unavailable: %s\n' % e)
    return dt / steps


def bench_bert(batch=32, seq_len=128, steps=20, cfg=None):
    """BASELINE.json config 2: BERT-base pretrain step time.

    At seq 128 the bf16 batched attention chain is the fast path (the
    Pallas flash kernels engage at seq >= cfg.flash_min_len where the
    [T,T] probs start to matter — see BENCHMARKS.md crossover)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    cfg = cfg or models.bert.BertConfig()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, enc, loss = models.bert.build_pretrain(cfg, seq_len)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Adam(1e-4),
            use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    batch_data = models.bert.synthetic_batch(cfg, batch, seq_len, rng)
    with _wpg(), fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        dt = _timed_steps(exe, main, batch_data, loss, steps)
    return dict({'metric': 'bert_base_pretrain_step_ms_b%d_s%d'
                 % (batch, seq_len),
                 'value': round(dt * 1000, 2), 'unit': 'ms/step',
                 'seq_per_sec': round(batch / dt, 1)},
                **LAST_PERF, **_step_phase_fields(),
                **_monitor_fields())


def bench_bert_long(batch=4, seq_len=2048, steps=10):
    """Long-context BERT step on the Pallas flash path (fused one-pass
    backward since round 5) — the configuration where the [T,T] probs
    would otherwise dominate HBM.  attn_dropout=0 keeps the metric
    comparable across rounds; bench_bert_long_dropout runs the
    reference-default config."""
    from paddle_tpu import models
    cfg = models.bert.BertConfig(max_pos=seq_len, attn_dropout=0.0)
    return dict(bench_bert(batch=batch, seq_len=seq_len, steps=steps,
                           cfg=cfg),
                metric='bert_base_long_ctx_step_ms_b%d_s%d'
                       % (batch, seq_len))


def bench_bert_long_dropout(batch=4, seq_len=2048, steps=10):
    """Long-context BERT with the REFERENCE-DEFAULT attention-prob
    dropout (0.1): since round 5 the dropout mask is drawn inside the
    flash kernels (counter hash keyed on op seed + step), so the
    [T, T] probs still never materialize — the last semantic asterisk
    on the long-context story (VERDICT r4 missing #1)."""
    from paddle_tpu import models
    cfg = models.bert.BertConfig(max_pos=seq_len, attn_dropout=0.1)
    return dict(bench_bert(batch=batch, seq_len=seq_len, steps=steps,
                           cfg=cfg),
                metric='bert_base_long_ctx_dropout_step_ms_b%d_s%d'
                       % (batch, seq_len))


def bench_resnet_infer(batch=32, steps=30, warmup=5):
    """Inference throughput through the deployment path: ResNet-50
    saved with save_inference_model, reloaded by AnalysisPredictor
    (the reference's inference stack ran this through TensorRT;
    here the predictor's program compiles to one XLA executable)."""
    import tempfile

    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.inference import AnalysisConfig, \
        create_paddle_predictor

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        img = fluid.layers.data('image', shape=[224, 224, 3],
                                dtype='float32')
        logits = models.resnet.resnet(img, 1000, depth=50,
                                      is_test=True,
                                      data_format='NHWC')
    import shutil
    model_dir = tempfile.mkdtemp(prefix='bench_infer_')
    try:
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            fluid.io.save_inference_model(model_dir, ['image'],
                                          [logits], exe,
                                          main_program=main)
        predictor = create_paddle_predictor(AnalysisConfig(model_dir))
    finally:
        shutil.rmtree(model_dir, ignore_errors=True)
    rng = np.random.RandomState(0)
    x = jax.device_put(
        rng.rand(batch, 224, 224, 3).astype('float32'))
    # pipelined serving throughput: dispatch stays async
    # (return_numpy=False), one blocking fetch closes the window —
    # per-request LATENCY additionally pays the tunnel round-trip here
    # (~100 ms), which an on-host deployment would not
    for _ in range(warmup):
        out = predictor.run_dict({'image': x}, return_numpy=False)
    np.asarray(out[0])
    t0 = time.time()
    for _ in range(steps):
        out = predictor.run_dict({'image': x}, return_numpy=False)
    np.asarray(out[0])
    dt = (time.time() - t0) / steps
    return dict({'metric': 'resnet50_infer_images_per_sec_b%d' % batch,
                 'value': round(batch / dt, 1), 'unit': 'images/sec'},
                **_monitor_fields())


def bench_wide_deep(batch=2048, steps=30, is_sparse=False):
    """BASELINE.json config 3: Wide&Deep CTR throughput.

    is_sparse=True measures the SPARSE path (SelectedRows-style
    row-scatter embedding grads + per-row adagrad) the CTR workload
    actually exercises at scale."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, preds, loss = models.wide_deep.build(
            models.wide_deep.BASE, is_sparse=is_sparse)
        fluid.optimizer.Adagrad(0.01).minimize(loss)
    cfg = models.wide_deep.BASE
    rng = np.random.RandomState(0)
    feed = models.wide_deep.synthetic_batch(cfg, batch, rng)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        dt = _timed_steps(exe, main, feed, loss, steps)
    return dict({'metric': 'wide_deep_ctr_examples_per_sec_b%d%s'
                 % (batch, '_sparse' if is_sparse else ''),
                 'value': round(batch / dt, 1),
                 'unit': 'examples/sec'},
                **LAST_PERF, **_step_phase_fields(),
                **_monitor_fields())


def bench_wide_deep_sparse(batch=2048, steps=30):
    return bench_wide_deep(batch, steps, is_sparse=True)


def bench_host_sparse_push(batch=4096, vocab=10_000_000, dim=16,
                           slots=20, steps=50):
    """The host-table sparse pull/push path itself (FleetWrapper
    PullSparse/PushSparse analog): a 10M-row table that could never
    live in HBM, O(touched rows) per step."""
    import time as _t
    from paddle_tpu.parallel.sparse_embedding import HostShardedEmbedding
    emb = HostShardedEmbedding('bench_big_emb', vocab, dim,
                               optimizer='adagrad', learning_rate=0.05,
                               initializer_scale=0, seed=1)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, slots)).astype('int64')
    grad = rng.randn(batch, slots, dim).astype('float32')
    emb._pull(ids)
    emb._push(ids, grad)
    t0 = _t.time()
    for _ in range(steps):
        emb._pull(ids)
        emb._push(ids, grad)
    dt = (_t.time() - t0) / steps
    del HostShardedEmbedding._REGISTRY['bench_big_emb']
    return {'metric': 'host_sparse_pull_push_examples_per_sec_b%d_v%dM'
            % (batch, vocab // 1_000_000),
            'value': round(batch / dt, 1), 'unit': 'examples/sec',
            'ms_per_step': round(dt * 1000, 3)}


def bench_rpc_sparse_push(batch=4096, vocab=10_000_000, dim=16,
                          slots=20, steps=50, n_servers=2):
    """The REMOTE sparse pull/push path: same workload as
    bench_host_sparse_push but the table lives in native pserver
    processes behind the framed-TCP protocol (runtime/ps_service.cc) —
    the listen_and_serv / parameter_prefetch leg the reference built
    gRPC zero-copy serde for (operators/distributed/grpc/
    grpc_serde.cc).  Measures the RPC overhead over the in-process
    number."""
    import time as _t
    from paddle_tpu.distributed import PsServer
    from paddle_tpu.parallel.sparse_embedding import (
        HostShardedEmbedding, RpcShardedEmbedding)
    servers = [PsServer() for _ in range(n_servers)]
    try:
        emb = RpcShardedEmbedding('bench_rpc_emb', vocab, dim,
                                  [s.endpoint for s in servers],
                                  optimizer='adagrad',
                                  learning_rate=0.05,
                                  initializer_scale=0)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, vocab, (batch, slots)).astype('int64')
        grad = rng.randn(batch, slots, dim).astype('float32')
        emb._pull(ids)
        emb._push(ids, grad)
        t0 = _t.time()
        for _ in range(steps):
            emb._pull(ids)
            emb._push(ids, grad)
        dt = (_t.time() - t0) / steps
        return {'metric':
                'rpc_sparse_pull_push_examples_per_sec_b%d_v%dM_s%d'
                % (batch, vocab // 1_000_000, n_servers),
                'value': round(batch / dt, 1), 'unit': 'examples/sec',
                'ms_per_step': round(dt * 1000, 3)}
    finally:
        HostShardedEmbedding._REGISTRY.pop('bench_rpc_emb', None)
        for s in servers:
            s.stop()


def bench_transformer(batch=32, src_len=64, tgt_len=64, steps=20):
    """BASELINE.json config 4: Transformer NMT step time."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, logits, loss = models.transformer.build(
            models.transformer.BASE, src_len, tgt_len)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Adam(1e-4),
            use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    cfg = models.transformer.BASE
    rng = np.random.RandomState(0)
    feed = models.transformer.synthetic_batch(cfg, batch, src_len,
                                              tgt_len, rng)
    with _wpg(), fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        dt = _timed_steps(exe, main, feed, loss, steps)
    return dict({'metric': 'transformer_nmt_tokens_per_sec_b%d' % batch,
                 'value': round(batch * tgt_len / dt, 1),
                 'unit': 'tokens/sec',
                 'step_ms': round(dt * 1000, 2)},
                **LAST_PERF, **_step_phase_fields(),
                **_monitor_fields())


def bench_resnet50_hostfed(batch=128, steps=20, warmup=3,
                           data_format='NHWC'):
    """ResNet-50 training fed from HOST memory through the async
    double-buffered DataLoader (capacity queue + 2-deep device_put
    window) — proves the input pipeline overlaps H2D with compute: the
    number should sit within a few % of the device-resident
    resnet50 entry (round-4 VERDICT item 4).  Note the feed here ALSO
    rides the tunnel, which an on-host deployment would not pay."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, logits, loss, acc = models.resnet.build(
            data_format=data_format)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Momentum(0.1, momentum=0.9),
            use_dynamic_loss_scaling=True)
        opt.minimize(loss)

    rng = np.random.RandomState(0)
    shape = (batch, 224, 224, 3) if data_format == 'NHWC' else \
        (batch, 3, 224, 224)
    # a couple of distinct host batches, cycled: the loader must
    # device_put fresh data each step (no accidental caching)
    host_batches = [
        {'image': rng.rand(*shape).astype('float32'),
         'label': rng.randint(0, 1000, (batch, 1)).astype('int32')}
        for _ in range(2)]

    n_total = warmup + steps

    def gen():
        for i in range(n_total):
            yield host_batches[i % 2]

    loader = fluid.io.DataLoader.from_generator(
        feed_list=[feeds['image'], feeds['label']], capacity=4,
        use_double_buffer=True)
    loader.set_batch_generator(gen)

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        it = iter(loader)
        for _ in range(warmup):
            exe.run(main, feed=next(it), fetch_list=[])
        l, = exe.run(main, feed=host_batches[0], fetch_list=[loss])
        np.asarray(l)
        t0 = time.time()
        n = 0
        for batch_data in it:
            exe.run(main, feed=batch_data, fetch_list=[])
            n += 1
        l, = exe.run(main, feed=host_batches[0], fetch_list=[loss])
        np.asarray(l)
        dt = time.time() - t0
        # baseline: the SAME host batches fed synchronously (numpy
        # straight into run, no background thread, no device window) —
        # the loader's overlap must beat this.  On the tunnel BOTH are
        # wire-bound (~77 MB/batch over the link), so the comparison,
        # not the absolute number, is the signal; an on-host deployment
        # pays PCIe instead and approaches the device-resident entry.
        t0 = time.time()
        for i in range(max(4, steps // 4)):
            exe.run(main, feed=host_batches[i % 2], fetch_list=[])
        l, = exe.run(main, feed=host_batches[0], fetch_list=[loss])
        np.asarray(l)
        sync_dt = (time.time() - t0) / (max(4, steps // 4) + 1)
    return dict({'metric': 'resnet50_train_hostfed_images_per_sec_b%d'
                 % batch,
                 'value': round(batch * (n + 1) / dt, 1),
                 'unit': 'images/sec',
                 'sync_feed_images_per_sec': round(batch / sync_dt, 1)},
                **_monitor_fields())


def bench_lenet(batch=512, steps=30, conv_precision=None):
    """BASELINE.json config 0: MNIST LeNet throughput.

    conv_precision: FLAGS_conv_precision override.  The service's
    compiler hangs on multi-pass (HIGHEST/HIGH) f32 weight-gradient
    convs at this model's b512/b256/b128 shapes (minimal repro:
    tools/repro_conv_wedge.py) — 'default' keeps the REQUESTED batch
    and downgrades only the conv algorithm, which is the principled
    fallback (vs the former b500 batch swap)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    prev_precision = fluid.flags.get_flag('FLAGS_conv_precision',
                                          'highest')
    if conv_precision:
        fluid.flags.set_flags({'FLAGS_conv_precision': conv_precision})
    try:
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 42
        with fluid.program_guard(main, startup):
            feeds, pred, loss, acc = models.lenet.build()
            fluid.optimizer.Adam(1e-3).minimize(loss)
        rng = np.random.RandomState(0)
        feed = {'img': rng.rand(batch, 1, 28, 28).astype('float32'),
                'label': rng.randint(0, 10,
                                     (batch, 1)).astype('int64')}
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            dt = _timed_steps(exe, main, feed, loss, steps)
    finally:
        # never leak a degraded precision into later in-process callers
        fluid.flags.set_flags({'FLAGS_conv_precision': prev_precision})
    return dict({'metric': 'lenet_mnist_images_per_sec_b%d' % batch,
                 'value': round(batch / dt, 1),
                 'unit': 'images/sec'},
                **LAST_PERF, **_step_phase_fields(),
                **_monitor_fields())


def bench_dispatch(depth=6, width=8, batch=4, steps=300, warmup=8):
    """Steady-state dispatch-side host cost per step, isolated: a tiny
    deep-ish MLP whose compute is ~free, fed device-resident data with
    no per-step fetch.  The device queue is drained OUTSIDE run() after
    every step, so `executor/run_seconds` sees pure host dispatch
    (binders, staging checks, jit call), never device backpressure —
    the metric the steady-state fast path moves; compute-bound entries
    bury it."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import monitor
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[width], dtype='float32')
        h = x
        for _ in range(depth):
            h = fluid.layers.fc(h, size=width, act='relu')
        loss = fluid.layers.reduce_mean(h)
        fluid.optimizer.SGD(0.01).minimize(loss)
    feed = {'x': jax.device_put(
        np.ones((batch, width), 'float32'))}
    pname = main.all_parameters()[0].name
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main, feed=feed, fetch_list=[])
        jax.block_until_ready(scope.find_var(pname))
        f0 = {k: v for k, v in monitor.flat().items()}
        for _ in range(steps):
            exe.run(main, feed=feed, fetch_list=[])
            jax.block_until_ready(scope.find_var(pname))
        f1 = monitor.flat()

    def d(key):
        return f1.get(key, 0.0) - f0.get(key, 0.0)

    per_step = d('executor/run_seconds/sum') / steps
    bind_n = d('executor/bind_seconds/count')
    return dict({'metric': 'dispatch_host_us_per_step_d%d' % depth,
                 'value': round(per_step * 1e6, 1),
                 'unit': 'us/step',
                 'fastpath_hit_rate': round(
                     d('executor/fastpath_hits') / steps, 3),
                 'bind_us_per_step': round(
                     1e6 * d('executor/bind_seconds/sum') /
                     max(bind_n, 1), 2)},
                **_monitor_fields())


def bench_cold_lenet(batch=64, steps=5, use_warmup=False):
    """--cold child: process-start -> first-train-step-complete wall
    time for LeNet (the metric a restarting/autoscaling service
    replica pays).  With FLAGS_compile_cache_dir set (the parent sets
    it), the first process populates the persistent segment store and
    the second starts from it; `use_warmup` additionally issues
    Executor.warmup right after the startup program so segment
    compilation (or disk loading) overlaps host-side setup."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import monitor
    from paddle_tpu import models
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, pred, loss, acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(batch, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (batch, 1)).astype('int64')}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        if use_warmup:
            exe.warmup(main, feed_shapes=feed, fetch_list=[loss])
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        first_loss = float(np.asarray(l).ravel()[0])  # block: step is
        t_first = time.time() - _PROC_T0              # COMPLETE
        t0 = time.time()
        for _ in range(steps - 1):
            exe.run(main, feed=feed, fetch_list=[])
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        np.asarray(l)
        steady = (time.time() - t0) / steps
    flat = monitor.flat()
    return {'metric': 'lenet_cold_start_to_first_step_s_b%d' % batch,
            'value': round(t_first, 3), 'unit': 'seconds',
            'steady_step_ms': round(steady * 1000, 2),
            'first_loss': first_loss,
            'compile_cache': {
                short: flat.get('executor/' + key, 0.0)
                for short, key in (
                    ('disk_hit', 'compile_cache_disk_hit'),
                    ('disk_miss', 'compile_cache_disk_miss'),
                    ('disk_writes', 'compile_cache_disk_writes'),
                    ('aot_compiles', 'aot_compiles'),
                    ('segments_lowered', 'segments_lowered'),
                    ('warmup_segments', 'warmup_segments'))}}


def _run_cold(cache_dir=None, out_path=None):
    """--cold driver: run bench_cold_lenet in three child processes
    against one FRESH private temp dir — cold (populates), warm
    (loads), and warm+warmup (loads in the background) — and print one
    JSON line per child plus a summary.  The bench NEVER touches
    PADDLE_TPU_COMPILE_CACHE_DIR / FLAGS_compile_cache_dir: 'cold'
    must mean cold, and wiping a user's shared production cache to get
    there is not this tool's call."""
    import shutil
    import subprocess
    import tempfile
    cleanup = cache_dir is None
    d = cache_dir or tempfile.mkdtemp(prefix='paddle_tpu_cold_')
    os.makedirs(d, exist_ok=True)
    results = {}
    for tag, kwargs in (('cold', {}), ('warm', {}),
                        ('warm_warmup', {'use_warmup': True})):
        env = dict(os.environ, FLAGS_compile_cache_dir=d)
        p = subprocess.run(
            [sys.executable, '-u', os.path.abspath(__file__), '--one',
             'cold_lenet', json.dumps(kwargs)],
            capture_output=True, text=True, timeout=900, env=env)
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith('{')]
        if not line:
            sys.stderr.write('cold child %s failed (rc=%d): %s\n'
                             % (tag, p.returncode, p.stderr[-300:]))
            continue
        rec = json.loads(line[-1])
        rec['phase'] = tag
        results[tag] = rec
        print(json.dumps(rec))
    if 'cold' in results and 'warm' in results:
        summary = {
            'metric': 'lenet_cold_vs_warm_start_s',
            'cold_s': results['cold']['value'],
            'warm_s': results['warm']['value'],
            'warm_warmup_s': results.get('warm_warmup',
                                         {}).get('value'),
            'speedup': round(results['cold']['value'] /
                             max(results['warm']['value'], 1e-9), 2),
            'warm_disk_hits':
                results['warm']['compile_cache']['disk_hit'],
            'warm_retraces':
                results['warm']['compile_cache']['segments_lowered'],
        }
        print(json.dumps(summary))
        if out_path:
            with open(out_path, 'w') as f:
                json.dump({'entries': list(results.values()),
                           'summary': summary}, f, indent=1,
                          sort_keys=True)
        if cleanup:
            shutil.rmtree(d, ignore_errors=True)
        return summary
    if cleanup:
        shutil.rmtree(d, ignore_errors=True)
    return None


def bench_elastic_save(batch=64, steps=4, store=None):
    """--elastic child: train LeNet under an fsdp2 layout (2 host
    devices, fc weights + Adam moments genuinely scattered via the
    auto-shard planner) and write one elastic checkpoint generation —
    the save-side bandwidth number (manifest + per-shard files +
    digests, atomic publish), and a SHARDED source so the resume
    child's reshard schedule prices real collectives."""
    import tempfile
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import elastic, monitor
    from paddle_tpu.parallel import plan as _ashard
    from paddle_tpu import models
    store = store or tempfile.mkdtemp(prefix='pt_elastic_bench_')
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, pred, loss, acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(batch, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (batch, 1)).astype('int64')}
    fluid.set_flags({'FLAGS_auto_shard': True})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        comp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name,
            places=[fluid.XLAPlace(i) for i in range(2)])
        comp._auto_plan = _ashard.build_plan(main, ndev=2,
                                             layouts=[(1, 2, 1)])
        for _ in range(steps):
            l, = exe.run(comp, feed=feed, fetch_list=[loss])
        first_loss = float(np.asarray(l).ravel()[0])
        t0 = time.time()
        gen = elastic.save_checkpoint(store, main, executor=exe)
        save_s = time.time() - t0
    flat = monitor.flat()
    save_bytes = flat.get('elastic/save_bytes', 0.0)
    return {'metric': 'elastic_checkpoint_save_bw_mbps_b%d' % batch,
            'value': round(save_bytes / max(save_s, 1e-9) / 1e6, 2),
            'unit': 'MB/s',
            'save_seconds': round(save_s, 4),
            'save_bytes': save_bytes,
            'shards': flat.get('elastic/shards_written', 0.0),
            'generation': gen, 'store': store,
            'loss_at_save': first_loss}


def bench_elastic_resume(batch=64, steps=3, store=None):
    """--elastic child: process-start -> resumed-first-step-complete
    wall time on a DIFFERENT topology (single device) — the N->M
    reconfiguration latency an autoscaling trainer pays, measured
    cold (empty compile cache) and warm (persistent store hit) by the
    driver.  Carries the reshard schedule's predicted-vs-measured
    honesty ratio and the load-side bandwidth."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import elastic, monitor
    from paddle_tpu import models
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, pred, loss, acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(batch, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (batch, 1)).astype('int64')}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        t0 = time.time()
        info = elastic.resume(exe, store, main, feed_shapes=feed,
                              fetch_list=[loss])
        lowered_after_warmup = monitor.counter_value(
            'executor/segments_lowered')
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        first_loss = float(np.asarray(l).ravel()[0])
        reconfig_s = time.time() - _PROC_T0
        resume_s = time.time() - t0
        for _ in range(steps - 1):
            exe.run(main, feed=feed, fetch_list=[loss])
        lowered_total = monitor.counter_value(
            'executor/segments_lowered')
    flat = monitor.flat()
    rs = info['reshard']
    return {'metric': 'elastic_reconfig_start_to_first_step_s_b%d'
                      % batch,
            'value': round(reconfig_s, 3), 'unit': 'seconds',
            'resume_s': round(resume_s, 3),
            'first_loss': first_loss,
            'loaded_generation': info['generation'],
            'load_seconds': info['seconds'],
            'load_bw_mbps': round(
                info['bytes'] / max(info['seconds'], 1e-9) / 1e6, 2),
            'reshard_predicted_s': rs['predicted_s'],
            'reshard_measured_s': rs['measured_s'],
            'reshard_pred_over_measured': rs['pred_over_measured'],
            'reshard_by_kind': rs['by_kind'],
            'staging_waves': rs['staging_waves'],
            'retraces_after_warmup': lowered_total -
                lowered_after_warmup,
            'compile_cache': {
                short: flat.get('executor/' + key, 0.0)
                for short, key in (
                    ('disk_hit', 'compile_cache_disk_hit'),
                    ('disk_writes', 'compile_cache_disk_writes'),
                    ('aot_compiles', 'aot_compiles'),
                    ('segments_lowered', 'segments_lowered'),
                    ('warmup_segments', 'warmup_segments'))}}


def _chaos_fields(stats):
    """--chaos summary: the soak's self-healing economics — recoveries
    vs injected fault kinds, lost work against the checkpoint
    cadence, checkpoint volume (incl. torn->resaved), and the bitwise
    post-recovery verification depth."""
    if not stats:
        return None
    return dict({
        'metric': 'chaos_soak_recoveries',
        'value': stats.get('recoveries'),
        'unit': 'recoveries',
    }, **stats)


def bench_chaos():
    """Drive the tools/check_chaos.py soak (the real multi-process
    chaos harness) and record its CHAOS_STATS line — one harness, one
    truth: the bench records exactly what the gate asserts."""
    import subprocess
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'tools', 'check_chaos.py')
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    try:
        p = subprocess.run([sys.executable, tool], env=env,
                           capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired as e:
        # a wedged soak is exactly what a chaos harness may produce:
        # record the outcome instead of dying without a BENCH entry
        return {'metric': 'chaos_soak_recoveries', 'value': None,
                'gate_rc': 'timeout',
                'gate_tail': (e.stdout or b'')[-1500:].decode(
                    'utf-8', 'replace') if isinstance(
                    e.stdout, bytes) else str(e.stdout)[-1500:]}
    stats = None
    for line in p.stdout.splitlines():
        if line.startswith('CHAOS_STATS '):
            stats = json.loads(line[len('CHAOS_STATS '):])
    rec = _chaos_fields(stats) or {'metric': 'chaos_soak_recoveries',
                                   'value': None}
    rec['gate_rc'] = p.returncode
    if p.returncode != 0:
        rec['gate_tail'] = p.stdout[-1500:]
    return rec


def _elastic_fields(results):
    """--elastic summary: cold vs warm N->M reconfiguration seconds
    through the persistent compile cache, the reshard schedule's
    predicted-vs-measured ratio, and checkpoint save/load
    bandwidth."""
    save, cold, warm = (results.get(k) for k in ('save', 'cold',
                                                 'warm'))
    if not (save and cold and warm):
        return None
    return {
        'metric': 'elastic_reconfig_cold_vs_warm_s',
        'cold_s': cold['value'],
        'warm_s': warm['value'],
        'speedup': round(cold['value'] / max(warm['value'], 1e-9), 2),
        'warm_disk_hits': warm['compile_cache']['disk_hit'],
        'warm_retraces_after_warmup': warm['retraces_after_warmup'],
        'save_bw_mbps': save['value'],
        'load_bw_mbps': warm['load_bw_mbps'],
        'reshard_pred_over_measured':
            warm['reshard_pred_over_measured'],
        'reshard_by_kind': warm['reshard_by_kind'],
    }


def _run_elastic(out_path=None):
    """--elastic driver: one dp2 child saves a generation, then two
    single-device children resume it against one FRESH compile-cache
    dir — cold (populates) and warm (disk hits, zero post-warmup
    retraces).  The topology change (2 devices -> 1) is the N->M
    reconfiguration being priced."""
    import shutil
    import subprocess
    import tempfile
    work = tempfile.mkdtemp(prefix='paddle_tpu_elastic_')
    store = os.path.join(work, 'store')
    cache = os.path.join(work, 'cache')
    results = {}
    jobs = (
        ('save', 'elastic_save', {'store': store},
         {'XLA_FLAGS': '--xla_force_host_platform_device_count=2'}),
        ('cold', 'elastic_resume', {'store': store}, {}),
        ('warm', 'elastic_resume', {'store': store}, {}),
    )
    try:
        for tag, name, kwargs, extra_env in jobs:
            env = dict(os.environ, FLAGS_compile_cache_dir=cache)
            env.update(extra_env)
            p = subprocess.run(
                [sys.executable, '-u', os.path.abspath(__file__),
                 '--one', name, json.dumps(kwargs)],
                capture_output=True, text=True, timeout=900, env=env)
            line = [ln for ln in p.stdout.splitlines()
                    if ln.startswith('{')]
            if not line:
                sys.stderr.write('elastic child %s failed (rc=%d): '
                                 '%s\n' % (tag, p.returncode,
                                           p.stderr[-400:]))
                continue
            rec = json.loads(line[-1])
            rec['phase'] = tag
            results[tag] = rec
            print(json.dumps(rec))
        summary = _elastic_fields(results)
        if summary:
            print(json.dumps(summary))
            if out_path:
                with open(out_path, 'w') as f:
                    json.dump({'cmd': 'JAX_PLATFORMS=cpu python '
                                      'bench.py --elastic',
                               'entries': list(results.values()),
                               'summary': summary}, f, indent=1,
                              sort_keys=True)
        return summary
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_serving(feeders=4, requests_per_feeder=100, max_batch=32,
                  burst=16):
    """Multi-client serving soak: N concurrent feeders, two resident
    programs (different input widths — mixed shapes), mixed row
    counts, through fluid.serving's continuous batcher — against a
    SEQUENTIAL baseline (one request at a time through Executor.run,
    the pre-serving posture).  Reports requests/sec for both arms,
    the speedup, per-request p50/p99 admission-to-completion latency,
    mean batch occupancy, and the post-warmup retrace count (must be
    0: every bucket comes from the warmed AOT ladder).  Step wall
    percentiles come straight out of trace.step_report() over the
    tenant-tagged serving steps."""
    import threading
    import jax  # noqa: F401 — device init before the timed regions
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import monitor, serving
    from paddle_tpu.fluid import trace as pt_trace

    def build(in_w, hid_w, seed):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[in_w], dtype='float32')
            h = fluid.layers.fc(x, hid_w, act='relu')
            y = fluid.layers.fc(h, 10, act='softmax')
        return main, startup, y

    exe = fluid.Executor(fluid.XLAPlace(0))
    tenants = {}
    for name, (in_w, hid_w, seed) in (('small', (16, 64, 21)),
                                      ('wide', (32, 96, 22))):
        mp, sp, y = build(in_w, hid_w, seed)
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(sp)
        tenants[name] = (mp, sc, y, in_w)
    rows_cycle = (1, 1, 2, 1, 4, 1)   # mostly single requests
    total_requests = feeders * requests_per_feeder

    def request_stream(seed):
        rng = np.random.RandomState(seed)
        for i in range(requests_per_feeder):
            name = ('small', 'wide')[(seed + i) % 2]
            rows = rows_cycle[i % len(rows_cycle)]
            in_w = tenants[name][3]
            yield name, rng.randn(rows, in_w).astype('float32')

    # -- sequential baseline: one blocking request at a time ---------
    for name, (mp, sc, y, in_w) in tenants.items():
        with fluid.scope_guard(sc):   # warm every shape out of band
            for rows in sorted(set(rows_cycle)):
                exe.run(mp, feed={'x': np.zeros((rows, in_w),
                                                'float32')},
                        fetch_list=[y])
    t0 = time.time()
    n_seq = 0
    for fid in range(feeders):
        for name, xv in request_stream(fid):
            mp, sc, y, _ = tenants[name]
            with fluid.scope_guard(sc):
                out, = exe.run(mp, feed={'x': xv}, fetch_list=[y])
            np.asarray(out)
            n_seq += 1
    seq_dt = time.time() - t0
    seq_rps = n_seq / seq_dt

    # -- continuous-batching soak ------------------------------------
    srv = serving.ServingExecutor(max_batch=max_batch, executor=exe)
    for name, (mp, sc, y, _w) in tenants.items():
        srv.add_program(name, mp, ['x'], [y], scope=sc)
    srv.warmup(wait=True)
    lowered0 = monitor.counter_value('executor/segments_lowered')
    trace_was_on = pt_trace.is_active()
    if not trace_was_on:
        pt_trace.enable(buffer_steps=2 * total_requests)
    latencies = []
    lat_lock = threading.Lock()

    def feeder(fid):
        pending = []
        for name, xv in request_stream(fid):
            t_sub = time.perf_counter()
            fut = srv.submit(name, {'x': xv})
            fut.add_done_callback(
                lambda _f, _t=t_sub: _record(_t))
            pending.append(fut)
            if len(pending) >= burst:
                for f in pending:   # pipelined: burst stays in flight
                    f.result(300)
                pending = []
        for f in pending:
            f.result(300)

    def _record(t_sub):
        done = time.perf_counter()
        with lat_lock:
            latencies.append(done - t_sub)

    t0 = time.time()
    threads = [threading.Thread(target=feeder, args=(fid,))
               for fid in range(feeders)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    soak_dt = time.time() - t0
    retraces = monitor.counter_value(
        'executor/segments_lowered') - lowered0
    try:
        rep = pt_trace.step_report()
        srv_steps = [s for s in rep['steps'] if s.get('tags')]
        walls = sorted(s['wall_ms'] for s in srv_steps)
        step_walls = {
            'count': len(walls),
            'wall_p50_ms': round(walls[len(walls) // 2], 3)
            if walls else 0.0,
            'wall_p99_ms': round(
                walls[min(len(walls) - 1,
                          int(0.99 * len(walls)))], 3)
            if walls else 0.0,
        }
    except Exception:
        step_walls = {}
    if not trace_was_on:
        pt_trace.disable()
        pt_trace.reset()
    srv_rps = len(latencies) / soak_dt
    occ = monitor.histogram_value('serving/batch_occupancy') or {}
    lat_sorted = sorted(latencies)
    srv.close()
    return dict({
        'metric': 'serving_requests_per_sec',
        'value': round(srv_rps, 1),
        'unit': 'req/s',
        'feeders': feeders,
        'programs': len(tenants),
        'requests': len(latencies),
        'sequential_rps': round(seq_rps, 1),
        'vs_sequential': round(srv_rps / max(seq_rps, 1e-9), 2),
        'latency_p50_ms': round(
            1e3 * _pct_of(lat_sorted, 0.50), 2),
        'latency_p99_ms': round(
            1e3 * _pct_of(lat_sorted, 0.99), 2),
        'mean_batch_occupancy': round(
            occ.get('sum', 0.0) / max(occ.get('count', 1), 1), 3),
        'batches': monitor.counter_value('serving/batches'),
        'pad_waste_bytes': monitor.counter_value(
            'serving/bucket_pad_waste_bytes'),
        'retraces_post_warmup': retraces,
        'serving_step_walls': step_walls,
    }, **_monitor_fields())


def _pct_of(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(round(q * (len(sorted_vals) - 1))))]


def bench_serving_fleet(feeders=3, requests_per_feeder=80,
                        max_batch=8):
    """Skewed-tenant churn soak, fleet vs single replica: the SAME
    workload (three tenants, ~70% of traffic on one hot tenant, mixed
    row counts) and the SAME churn events (the hot tenant is
    relocated twice mid-soak) through two arms —

    - single replica: churn is evict -> re-register -> re-warm ON the
      serving path; requests to the hot tenant stall (retried at
      admission) until the re-warm finishes, so tail latency eats the
      whole warmup wall;
    - two-replica fleet: churn is ``fleet.migrate`` — the target is
      pre-warmed through the persistent compile cache while the
      SOURCE keeps serving, then the route flips; no request ever
      waits on a warmup.

    Reports per-request p50/p99 for both arms (the acceptance claim:
    fleet p99 held under churn while the single replica degrades),
    zero post-warmup retraces, and every migration matched to a
    priced decision in the fleet log."""
    import threading
    import jax  # noqa: F401 — device init before the timed regions
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import fleet, memviz, monitor, serving

    def build(hid_w, seed):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[16], dtype='float32')
            h = fluid.layers.fc(x, hid_w, act='relu')
            y = fluid.layers.fc(h, 10, act='softmax')
        return main, startup, y

    exe = fluid.Executor(fluid.XLAPlace(0))
    tenants = {}
    for name, (hid_w, seed) in (('hot', (64, 31)), ('warm', (96, 32)),
                                ('cold', (48, 33))):
        mp, sp, y = build(hid_w, seed)
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(sp)
        tenants[name] = (mp, sc, y)
    # ~70% of traffic on the hot tenant — the skew churn then hits
    skew = ('hot', 'hot', 'hot', 'warm', 'hot',
            'cold', 'hot', 'hot', 'warm', 'hot')
    rows_cycle = (1, 1, 2, 1, 4, 1)
    total = feeders * requests_per_feeder

    def run_arm(submit_fn, churn_fn):
        """One soak: N feeders over the skewed stream, churn fired at
        1/3 and 2/3 progress.  A submit that lands mid-churn (tenant
        momentarily unregistered on the single arm) retries at
        admission — the wait counts against its latency, which is the
        point."""
        latencies = []
        lock = threading.Lock()
        served = [0]
        errors = []
        churn_walls = []

        def feeder(fid):
            rng = np.random.RandomState(200 + fid)
            for i in range(requests_per_feeder):
                name = skew[(fid + i) % len(skew)]
                rows = rows_cycle[i % len(rows_cycle)]
                xv = rng.randn(rows, 16).astype('float32')
                t0 = time.perf_counter()
                try:
                    while True:
                        try:
                            fut = submit_fn(name, {'x': xv})
                            break
                        except KeyError:
                            time.sleep(0.002)   # tenant mid-churn
                    fut.result(300)
                except Exception as e:  # noqa: BLE001
                    errors.append('%s req %d: %s' % (name, i, e))
                    continue
                lat = time.perf_counter() - t0
                with lock:
                    latencies.append(lat)
                    served[0] += 1

        def churner():
            for frac in (1 / 3, 2 / 3):
                while served[0] < frac * total:
                    time.sleep(0.005)
                t0 = time.perf_counter()
                churn_fn()
                churn_walls.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=feeder, args=(fid,))
                   for fid in range(feeders)]
        ct = threading.Thread(target=churner)
        t0 = time.time()
        for t in threads:
            t.start()
        ct.start()
        for t in threads:
            t.join(600)
        ct.join(60)
        dt = time.time() - t0
        lat = sorted(latencies)
        return {'requests': len(latencies), 'wall_s': dt,
                'rps': len(latencies) / dt,
                'p50_ms': 1e3 * _pct_of(lat, 0.50),
                'p99_ms': 1e3 * _pct_of(lat, 0.99),
                'churn_walls_s': [round(w, 3) for w in churn_walls],
                'errors': errors[:3]}

    # -- arm 1: single replica, churn on the serving path ------------
    srv = serving.ServingExecutor(max_batch=max_batch, executor=exe)
    for name, (mp, sc, y) in tenants.items():
        srv.add_program(name, mp, ['x'], [y], scope=sc)
    srv.warmup(wait=True)
    lowered0 = monitor.counter_value('executor/segments_lowered')

    def churn_single():
        # relocation without a second replica: the tenant leaves the
        # ladder and re-warms IN the serving path — its traffic waits
        mp, sc, y = tenants['hot']
        srv.remove_program('hot', drain=True)
        srv.add_program('hot', mp, ['x'], [y], scope=sc)
        srv.warmup_tenant('hot', wait=True)

    def submit_single(name, feed):
        # the readiness contract (serving.readiness): an unwarmed
        # tenant makes the replica unready — a load balancer holds
        # traffic until the re-warm finishes, so the wait lands on
        # the requests' latency
        t = srv._tenants.get(name)
        if t is None or not t.warmed:
            raise KeyError(name)
        return srv.submit(name, feed)

    single = run_arm(submit_single, churn_single)
    single_retraces = monitor.counter_value(
        'executor/segments_lowered') - lowered0
    srv.close()

    # -- arm 2: two-replica fleet, churn is a priced migration -------
    fl = fleet.Fleet()
    for i in range(2):
        fl.add_replica('r%d' % i,
                       serving.ServingExecutor(max_batch=max_batch,
                                               executor=exe))
    for name, (mp, sc, y) in tenants.items():
        fl.register_tenant(name, mp, ['x'], [y], scope=sc)
    fl.warmup(wait=True)
    memviz.live_census()       # the migration pricing input
    lowered0 = monitor.counter_value('executor/segments_lowered')

    fleet_arm = run_arm(fl.submit,
                        lambda: fl.migrate('hot', why='churn'))
    fleet_retraces = monitor.counter_value(
        'executor/segments_lowered') - lowered0
    moves = [d for d in fleet.decisions()
             if d['kind'] in ('migrate', 'evict') and d['acted']]
    unpriced = [d for d in moves if 'priced' not in d.get('info', {})]
    for s in fl.replicas().values():
        s.close()
    fl.close()

    return dict({
        'metric': 'serving_fleet_p99_ms',
        'value': round(fleet_arm['p99_ms'], 2),
        'unit': 'ms',
        'feeders': feeders,
        'replicas': 2,
        'programs': len(tenants),
        'requests': fleet_arm['requests'],
        'fleet_p50_ms': round(fleet_arm['p50_ms'], 2),
        'fleet_rps': round(fleet_arm['rps'], 1),
        'fleet_churn_walls_s': fleet_arm['churn_walls_s'],
        'fleet_errors': fleet_arm['errors'],
        # the degrading arm: same workload, same churn, one replica.
        # Deliberately NOT regression-gated (vs_baseline): its p99 IS
        # the churn warmup wall, an environmental quantity
        'single_replica_churn_p99_ms_vs_baseline':
            round(single['p99_ms'], 2),
        'single_replica_churn_p50_ms_vs_baseline':
            round(single['p50_ms'], 2),
        'single_replica_rps_vs_baseline': round(single['rps'], 1),
        'single_churn_walls_s_vs_baseline':
            single['churn_walls_s'],
        'single_errors_vs_baseline': single['errors'],
        'p99_held_under_churn':
            bool(fleet_arm['p99_ms'] <= single['p99_ms']),
        'retraces_post_warmup': fleet_retraces,
        'single_retraces_post_warmup_vs_baseline': single_retraces,
        'migrations': monitor.counter_value('fleet/migrations'),
        'priced_moves': len(moves),
        'unpriced_moves': len(unpriced),
        'routed_requests': monitor.counter_value(
            'fleet/routed_requests'),
        'fleet_decisions': len(fleet.decisions()),
    }, **_monitor_fields())


def bench_health_overhead(depth=4, width=64, batch=32, steps=60,
                          warmup=8):
    """FLAGS_health_summaries on/off A/B on one small MLP: the BENCH
    JSON records the per-step cost of the opt-in tensor-health
    reductions AND enforces the 'costs nothing when off' claim — the
    off posture must match the plain dispatch profile (summaries
    record zero health counters), and the on posture's overhead is
    published so a regression (e.g. a reduction that starts blocking
    per param) is visible in the trajectory, not just in a gate."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import health, monitor

    def build(seed):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[width], dtype='float32')
            h = x
            for _ in range(depth):
                h = fluid.layers.fc(h, size=width, act='relu')
            loss = fluid.layers.reduce_mean(fluid.layers.square(h))
            fluid.optimizer.SGD(0.01).minimize(loss)
        return main, startup, loss

    feed = {'x': jax.device_put(np.ones((batch, width), 'float32'))}

    def timed(flag_on, seed):
        # the flag keys the PLAN (param grads surface as segment
        # outputs), so each posture builds its own program
        fluid.flags.set_flags({'FLAGS_health_summaries': flag_on})
        health.reset_state()
        try:
            main, startup, loss = build(seed)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.XLAPlace(0))
                exe.run(startup)
                for _ in range(warmup):
                    exe.run(main, feed=feed, fetch_list=[])
                pname = main.all_parameters()[0].name
                jax.block_until_ready(scope.find_var(pname))
                t0 = time.time()
                for _ in range(steps):
                    exe.run(main, feed=feed, fetch_list=[])
                    jax.block_until_ready(scope.find_var(pname))
                return (time.time() - t0) / steps
        finally:
            fluid.flags.set_flags({'FLAGS_health_summaries': False})

    off_s = timed(False, 42)
    recorded_off = monitor.counter_value('health/summary_steps')
    on_s = timed(True, 42)
    recorded_on = monitor.counter_value('health/summary_steps') - \
        recorded_off
    return dict({'metric': 'health_overhead_us_per_step_d%d' % depth,
                 'value': round((on_s - off_s) * 1e6, 1),
                 'unit': 'us/step',
                 'health_overhead': {
                     'off_us_per_step': round(off_s * 1e6, 1),
                     'on_us_per_step': round(on_s * 1e6, 1),
                     'overhead_pct': round(
                         100.0 * (on_s - off_s) / max(off_s, 1e-12),
                         1),
                     'summaries_recorded_off': recorded_off,
                     'summaries_recorded_on': recorded_on}},
                **_monitor_fields())


def bench_memviz_overhead(depth=4, width=64, batch=32, steps=60,
                          warmup=8):
    """FLAGS_memviz on/off A/B on one small MLP: the BENCH JSON
    records the per-step cost of the live-HBM sampler (census over
    jax.live_arrays() + gauges + counter track) AND enforces the
    'costs one flag read when off' claim — the off posture must record
    zero census samples (tools/check_memviz.py gates the counter
    budgets; this publishes the wall-clock trajectory so a sampler
    that starts blocking per step is visible in the numbers)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import memviz, monitor

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[width], dtype='float32')
        h = x
        for _ in range(depth):
            h = fluid.layers.fc(h, size=width, act='relu')
        loss = fluid.layers.reduce_mean(fluid.layers.square(h))
        fluid.optimizer.SGD(0.01).minimize(loss)
    feed = {'x': jax.device_put(np.ones((batch, width), 'float32'))}

    def timed(flag_on):
        # the flag gates only the post-step sampler (never the plan or
        # the lowering), so both postures share one program + executor
        fluid.flags.set_flags({'FLAGS_memviz': flag_on})
        try:
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.XLAPlace(0))
                exe.run(startup)
                for _ in range(warmup):
                    exe.run(main, feed=feed, fetch_list=[])
                pname = main.all_parameters()[0].name
                jax.block_until_ready(scope.find_var(pname))
                t0 = time.time()
                for _ in range(steps):
                    exe.run(main, feed=feed, fetch_list=[])
                    jax.block_until_ready(scope.find_var(pname))
                return (time.time() - t0) / steps
        finally:
            fluid.flags.set_flags({'FLAGS_memviz': False})

    memviz.reset()
    off_s = timed(False)
    samples_off = monitor.counter_value('memviz/samples')
    on_s = timed(True)
    samples_on = monitor.counter_value('memviz/samples') - samples_off
    return dict({'metric': 'memviz_overhead_us_per_step_d%d' % depth,
                 'value': round((on_s - off_s) * 1e6, 1),
                 'unit': 'us/step',
                 'memviz_overhead': {
                     'off_us_per_step': round(off_s * 1e6, 1),
                     'on_us_per_step': round(on_s * 1e6, 1),
                     'overhead_pct': round(
                         100.0 * (on_s - off_s) / max(off_s, 1e-12),
                         1),
                     'samples_recorded_off': samples_off,
                     'samples_recorded_on': samples_on,
                     'live_bytes_total': monitor.gauge_value(
                         'memviz/live_bytes_total')}},
                **_monitor_fields())


def bench_opprof_overhead(depth=4, width=64, batch=32, steps=60,
                          warmup=8):
    """FLAGS_opprof on/off A/B on one small MLP: the BENCH JSON
    records the per-step cost of the op-cost attribution plane
    (snapshot-step survivable copies + the synchronous dispatch that
    measures the segment wall) AND enforces the 'costs one flag read
    when off' claim — the off posture must record zero segment
    snapshots (tools/check_opprof.py gates the counter budgets; this
    publishes the wall-clock trajectory so a snapshot path that
    starts leaking into non-snapshot steps is visible).  The flag is
    fingerprint-neutral, so both postures share one program +
    executor — flipping it mid-run causes zero retraces."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import monitor, opprof

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[width], dtype='float32')
        h = x
        for _ in range(depth):
            h = fluid.layers.fc(h, size=width, act='relu')
        loss = fluid.layers.reduce_mean(fluid.layers.square(h))
        fluid.optimizer.SGD(0.01).minimize(loss)
    feed = {'x': jax.device_put(np.ones((batch, width), 'float32'))}

    def timed(flag_on):
        # the flag gates only the snapshot/instance-naming plane
        # (never the plan or the fingerprint), so both postures share
        # one program + executor
        fluid.flags.set_flags({'FLAGS_opprof': flag_on})
        try:
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.XLAPlace(0))
                exe.run(startup)
                for _ in range(warmup):
                    exe.run(main, feed=feed, fetch_list=[])
                pname = main.all_parameters()[0].name
                jax.block_until_ready(scope.find_var(pname))
                t0 = time.time()
                for _ in range(steps):
                    exe.run(main, feed=feed, fetch_list=[])
                    jax.block_until_ready(scope.find_var(pname))
                return (time.time() - t0) / steps
        finally:
            fluid.flags.set_flags({'FLAGS_opprof': False})

    opprof.reset()
    off_s = timed(False)
    snaps_off = monitor.counter_value('opprof/snapshots')
    on_s = timed(True)
    snaps_on = monitor.counter_value('opprof/snapshots') - snaps_off
    return dict({'metric': 'opprof_overhead_us_per_step_d%d' % depth,
                 'value': round((on_s - off_s) * 1e6, 1),
                 'unit': 'us/step',
                 'opprof_overhead': {
                     'off_us_per_step': round(off_s * 1e6, 1),
                     'on_us_per_step': round(on_s * 1e6, 1),
                     'overhead_pct': round(
                         100.0 * (on_s - off_s) / max(off_s, 1e-12),
                         1),
                     'snapshots_recorded_off': snaps_off,
                     'snapshots_recorded_on': snaps_on}},
                **_monitor_fields())


def bench_parallel(batch=256, width=256, steps=30, warmup=5,
                   skew_seconds=20.0):
    """Collective-job bench (BENCH_comms.json): a GradAllReduce MLP
    over the host's device mesh measures bytes_on_wire per step and
    per-(collective, size-bucket) achieved bandwidth through the
    fluid.comms telemetry; a real two-subprocess job (rank 1 fed a 4x
    batch — a genuine straggler) then reports cross-rank skew from the
    rank-0 aggregator and the merged job timeline from
    trace.collect_job — so future collective PRs (ROADMAP item 3) can
    name what they moved."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import comms, layers, monitor
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce

    ndev = len(jax.devices())
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[width], dtype='float32')
        h = layers.fc(x, width, act='relu')
        h = layers.fc(h, width, act='relu')
        loss = layers.reduce_mean(layers.fc(h, 1))
        fluid.optimizer.SGD(0.1).minimize(loss)
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')
    exe = fluid.Executor(fluid.XLAPlace(0))
    rng = np.random.RandomState(0)
    feed = {'x': rng.rand(batch, width).astype('float32')}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        wire0 = monitor.counter_value('comms/bytes_on_wire')
        t0 = time.perf_counter()
        for _ in range(steps):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        wall = time.perf_counter() - t0
        wire = monitor.counter_value('comms/bytes_on_wire') - wire0
    bw = {}
    for (kind, bucket), samples in sorted(comms.bw_samples().items()):
        s = sorted(samples)
        bw['%s/%s' % (kind, bucket)] = {
            'p50_gbps': round(s[len(s) // 2], 6),
            'max_gbps': round(s[-1], 6),
            'samples': len(s)}
    rec = {
        'metric': 'parallel_comms',
        'value': round(steps / wall, 2),
        'unit': 'steps/sec',
        'devices': ndev,
        'batch': batch,
        'bytes_on_wire_per_step': round(wire / max(1, steps), 1),
        'payload_bytes_total':
            monitor.counter_value('comms/payload_bytes'),
        'bandwidth': bw,
    }
    rec['plan_ab'] = _plan_ab_fields(batch=batch, width=width)
    rec.update(_skew_job_fields(skew_seconds))
    rec.update(_monitor_fields())
    return rec


def _plan_ab_fields(batch=256, width=256, rounds=6, per_round=4,
                    warmup=3):
    """Per-arm collective-planner A/B (interleaved): the same
    GradAllReduce MLP transpiled three ways — v1.6 dense flat
    (planner off), planned fused dense, planned quantized — each with
    its own program + scope + executable (the planner digest keys the
    fingerprints apart), timed in interleaved bursts so OS noise hits
    every arm equally.  Reports steps/sec, bytes-on-wire per step and
    the quantized arm's wire reduction vs dense, plus final losses so
    the parity claim rides in the artifact."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce

    # every arm pins the model path to a guaranteed-empty file so an
    # ambient ./comms_model.json (README's calibrate-then-bench order)
    # cannot flip the dense arms onto rs_ag and mislabel the A/B
    no_model = {'FLAGS_comms_model_path': os.devnull}
    arms = (
        ('dense_flat', dict(no_model, **{'FLAGS_comms_plan': False,
                                         'FLAGS_comms_quantize':
                                             False})),
        ('fused_dense', dict(no_model, **{'FLAGS_comms_plan': True,
                                          'FLAGS_comms_quantize':
                                              False})),
        ('quant', dict(no_model, **{'FLAGS_comms_plan': True,
                                    'FLAGS_comms_quantize': True,
                                    'FLAGS_comms_quantize_min_bytes':
                                        4096})),
    )
    keys = sorted({k for _, fl in arms for k in fl} |
                  {'FLAGS_comms_quantize_min_bytes'})
    prev = fluid.get_flags(keys)
    rng = np.random.RandomState(0)
    feed = {'x': rng.rand(batch, width).astype('float32'),
            'y': rng.rand(batch, 1).astype('float32')}
    setups = {}
    out = {}
    try:
        for name, fl in arms:
            fluid.set_flags(fl)
            main_p, startup = fluid.Program(), fluid.Program()
            main_p.random_seed = startup.random_seed = 7
            with fluid.program_guard(main_p, startup):
                x = layers.data('x', shape=[width], dtype='float32')
                y = layers.data('y', shape=[1], dtype='float32')
                h = layers.fc(x, width, act='relu')
                h = layers.fc(h, width, act='relu')
                # bounded regression objective: losses stay finite so
                # the per-arm parity rides in the artifact
                loss = layers.reduce_mean(layers.square_error_cost(
                    layers.fc(h, 1), y))
                fluid.optimizer.SGD(0.01).minimize(loss)
            GradAllReduce().transpile(startup, main_p, 0,
                                      ['127.0.0.1:0'], '127.0.0.1:0')
            scope = fluid.Scope()
            # one Executor PER ARM: parameter init folds the
            # executor's step counter into its RNG, so a shared
            # executor would hand each arm a different init and break
            # the cross-arm loss comparison
            exe = fluid.Executor(fluid.XLAPlace(0))
            with fluid.scope_guard(scope):
                exe.run(startup)
                for _ in range(warmup):
                    exe.run(main_p, feed=feed, fetch_list=[loss])
            setups[name] = {'flags': fl, 'program': main_p,
                            'loss': loss, 'scope': scope, 'exe': exe,
                            'walls': [], 'wire': 0.0, 'steps': 0,
                            'final_loss': None}
        for _ in range(rounds):
            for name, _fl in arms:
                s = setups[name]
                fluid.set_flags(s['flags'])
                with fluid.scope_guard(s['scope']):
                    w0 = monitor.counter_value('comms/bytes_on_wire')
                    t0 = time.perf_counter()
                    for _ in range(per_round):
                        lv, = s['exe'].run(s['program'], feed=feed,
                                           fetch_list=[s['loss']])
                    s['walls'].append(time.perf_counter() - t0)
                    s['wire'] += monitor.counter_value(
                        'comms/bytes_on_wire') - w0
                    s['steps'] += per_round
                    s['final_loss'] = float(np.asarray(lv))
        for name, s in setups.items():
            best = min(s['walls']) / per_round
            out[name] = {
                'steps_per_sec': round(per_round / min(s['walls']), 2),
                'best_step_ms': round(best * 1e3, 3),
                'bytes_on_wire_per_step':
                    round(s['wire'] / max(1, s['steps']), 1),
                'final_loss': s['final_loss'],
            }
        dense = out.get('fused_dense', {})
        quant = out.get('quant', {})
        flat = out.get('dense_flat', {})
        if dense.get('bytes_on_wire_per_step') and \
                quant.get('bytes_on_wire_per_step'):
            out['quant_wire_reduction_x'] = round(
                dense['bytes_on_wire_per_step'] /
                quant['bytes_on_wire_per_step'], 2)
        if flat.get('best_step_ms') and dense.get('best_step_ms'):
            out['fused_vs_flat_step_delta_pct'] = round(
                100.0 * (dense['best_step_ms'] - flat['best_step_ms'])
                / flat['best_step_ms'], 1)
    finally:
        fluid.set_flags(prev)
    return out


def bench_kernels(rounds=6, per_round=4, warmup=3):
    """Pallas kernel-library interleaved A/B (BENCH_kernels.json):
    per kernel, the shipped auto-dispatch arm vs the kernel flag
    forced off (dense reference), same program and feed, timed in
    interleaved bursts so OS noise hits both arms equally.

    Honest-A/B bookkeeping rides in the artifact: each entry records
    the pallas/<kernel>/dispatch_{fused,dense} deltas (so a silent
    dense fallback — the CPU posture, where both arms lower the same
    dense reference and must tie — can never masquerade as a fused
    win), the post-warmup retrace count (executor segments_lowered
    delta across the timed rounds, which must be zero: dispatch is a
    trace-time decision keyed into the lowering fingerprint), and the
    final losses for the parity claim."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor
    from paddle_tpu.ops.pallas import common as pallas_common

    rng = np.random.RandomState(0)

    def opt_net():
        # feed from a PINNED seed: both arms of an entry must see the
        # same batch or the cross-arm loss comparison is noise
        feed_rng = np.random.RandomState(1)
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = 7
        with fluid.program_guard(main_p, startup):
            x = layers.data('x', shape=[128], dtype='float32')
            h = layers.fc(x, 128, act='relu')
            h = layers.fc(h, 128, act='relu')
            loss = layers.reduce_mean(layers.square(layers.fc(h, 8)))
            fluid.optimizer.Adam(1e-3).minimize(loss)
        return main_p, startup, loss, \
            {'x': feed_rng.rand(64, 128).astype('float32')}

    def emb_net():
        feed_rng = np.random.RandomState(2)
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = 7
        with fluid.program_guard(main_p, startup):
            ids = layers.data('ids', shape=[1], dtype='int64')
            emb = layers.embedding(ids, size=[4096, 64])
            loss = layers.reduce_mean(
                layers.square(layers.fc(emb, 8)))
            fluid.optimizer.Adagrad(0.05).minimize(loss)
        return main_p, startup, loss, \
            {'ids': feed_rng.randint(0, 4096,
                                     size=(64, 1)).astype('int64')}

    out = {}
    for kernel, build, flag in (
            ('fused_optimizer', opt_net, 'FLAGS_pallas_opt_fuse'),
            ('embedding_update', emb_net, 'FLAGS_pallas_embedding')):
        prev = fluid.get_flags([flag])
        disp0 = {k: monitor.counter_value('pallas/%s/dispatch_%s'
                                          % (kernel, k))
                 for k in ('fused', 'dense')}
        arms = {}
        try:
            for arm, on in (('auto', True), ('dense', False)):
                fluid.set_flags({flag: on})
                main_p, startup, loss, feed = build()
                scope = fluid.Scope()
                exe = fluid.Executor(fluid.XLAPlace(0))
                with fluid.scope_guard(scope):
                    exe.run(startup)
                    for _ in range(warmup):
                        exe.run(main_p, feed=feed, fetch_list=[loss])
                arms[arm] = {'on': on, 'program': main_p,
                             'loss': loss, 'feed': feed,
                             'scope': scope, 'exe': exe, 'walls': [],
                             'final_loss': None}
            lowered0 = monitor.counter_value(
                'executor/segments_lowered')
            for _ in range(rounds):
                for arm in ('auto', 'dense'):
                    s = arms[arm]
                    fluid.set_flags({flag: s['on']})
                    with fluid.scope_guard(s['scope']):
                        t0 = time.perf_counter()
                        for _ in range(per_round):
                            lv, = s['exe'].run(s['program'],
                                               feed=s['feed'],
                                               fetch_list=[s['loss']])
                        s['walls'].append(time.perf_counter() - t0)
                        s['final_loss'] = float(np.asarray(lv))
            rec = {
                'post_warmup_retraces': int(
                    monitor.counter_value('executor/segments_lowered')
                    - lowered0),
            }
            for arm in ('auto', 'dense'):
                s = arms[arm]
                rec[arm] = {
                    'steps_per_sec': round(
                        per_round / min(s['walls']), 2),
                    'best_step_ms': round(
                        min(s['walls']) / per_round * 1e3, 3),
                    'final_loss': s['final_loss'],
                }
            for k in ('fused', 'dense'):
                rec['dispatch_%s_count' % k] = monitor.counter_value(
                    'pallas/%s/dispatch_%s' % (kernel, k)) - disp0[k]
            out[kernel] = rec
        finally:
            fluid.set_flags(prev)

    # quantized-collective element phases, kernel level: the wire
    # collectives are identical in both arms, so the A/B times the
    # quantize + dequant/reduce/requant chain itself (jitted); off-TPU
    # the fused arm runs the Pallas interpreter and is labeled so
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import quant_collective as qc
    n_ranks, cb, block = 8, 16, 256
    xq = jnp.asarray(
        rng.randn(n_ranks * cb, block).astype('float32'))
    traces = {'dense': 0, 'fused': 0}

    def _q(t):
        s = jnp.max(jnp.abs(t), axis=-1, keepdims=True) / 127.0
        s = jnp.where(s > 0, s, 1.0)
        return (jnp.clip(jnp.rint(t / s), -127, 127).astype(jnp.int8),
                s.astype(jnp.float32))

    def dense_fn(v):
        traces['dense'] += 1
        qv, s = _q(v.reshape(n_ranks, cb, block))
        red = jnp.sum(qv.astype(jnp.float32) * s, axis=0)
        return _q(red)

    interp = not pallas_common.on_tpu()

    def fused_fn(v):
        traces['fused'] += 1
        qv, s = qc.quantize_blocks(v, interp)
        return qc.dequant_reduce_requant(
            qv.reshape(n_ranks, cb, block),
            s.reshape(n_ranks, cb, 1), interp)

    jd, jf = jax.jit(dense_fn), jax.jit(fused_fn)
    rd, rf = jd(xq), jf(xq)
    parity = bool(
        np.array_equal(np.asarray(rd[0]), np.asarray(rf[0])) and
        np.array_equal(np.asarray(rd[1]), np.asarray(rf[1])))
    walls = {'dense': [], 'fused': []}
    for _ in range(rounds):
        for name, fn in (('dense', jd), ('fused', jf)):
            t0 = time.perf_counter()
            for _ in range(per_round):
                r = fn(xq)
            np.asarray(r[0])
            walls[name].append(time.perf_counter() - t0)
    out['quant_collective'] = {
        'dense': {'best_call_ms': round(
            min(walls['dense']) / per_round * 1e3, 3)},
        'fused': {'best_call_ms': round(
            min(walls['fused']) / per_round * 1e3, 3),
            'path': 'tpu' if not interp else 'interpret'},
        'post_warmup_retraces':
            traces['dense'] + traces['fused'] - 2,
        'parity_bitwise': parity,
    }
    return {'metric': 'pallas_kernels_ab', 'value': float(
        sum(v.get('post_warmup_retraces', 0) for v in out.values())),
        'unit': 'post_warmup_retraces', 'kernels': out}


def bench_autopilot(adapt_steps=60, rounds=4, per_round=3, warmup=2):
    """Closed-loop autopilot A/B (BENCH_autopilot.json): the SAME
    GradAllReduce MLP under the SAME faultinjected fabric drift
    (`collective.dispatch:delay` landing inside the measured dispatch
    wall), three ways — a STALE static comms model calibrated
    pre-drift, the autopilot arm starting from that same stale model
    but allowed to refit online, and a hand-tuned reference
    calibrated WITH the drift armed (the oracle the autopilot should
    converge toward).  The adaptation phase runs first on the
    autopilot arm alone (refits counted; the pending refit must move
    no digest); the reported numbers come from interleaved bursts so
    OS noise hits every arm equally, with the in-memory refit
    installed ONLY during the autopilot arm's bursts — account-time
    repricing is process-global, so leaving it installed would
    silently heal the static arms' honesty too.  Honesty per arm is
    delta(plan_predicted)/delta(plan_measured) over its own bursts."""
    import tempfile
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import (autopilot, comms, comms_plan,
                                  faultinject, layers, monitor)
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce

    tmp = tempfile.mkdtemp(prefix='bench_autopilot_')
    stale_path = os.path.join(tmp, 'stale_model.json')
    tuned_path = os.path.join(tmp, 'tuned_model.json')
    drift_spec = 'collective.dispatch:delay:0.05@1+'
    keys = ['FLAGS_comms_plan', 'FLAGS_comms_model_path',
            'FLAGS_comms_bucket_bytes', 'FLAGS_timeseries',
            'FLAGS_autopilot', 'FLAGS_autopilot_interval_s']
    prev = fluid.get_flags(keys)
    rng = np.random.RandomState(0)
    feed = {'x': rng.rand(64, 64).astype('float32')}

    def build():
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = 7
        with fluid.program_guard(main_p, startup):
            x = layers.data('x', shape=[64], dtype='float32')
            # weight grads land in distinct wire size buckets so the
            # two-parameter refit stays identifiable from live points
            h = layers.fc(x, 1024, act='relu')
            h = layers.fc(h, 32, act='relu')
            loss = layers.reduce_mean(h)
            fluid.optimizer.SGD(0.01).minimize(loss)
        GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                                  '127.0.0.1:0')
        return main_p, startup, loss

    def _pm():
        return (monitor.counter_value('comms/plan_predicted_seconds')
                or 0.0,
                monitor.counter_value('comms/plan_measured_seconds')
                or 0.0)

    def _honesty(p0m0, p1m1):
        dp, dm = p1m1[0] - p0m0[0], p1m1[1] - p0m0[1]
        return round(dp / dm, 4) if dm > 0 else None

    def _lowered():
        return ((monitor.counter_value('executor/segments_lowered')
                 or 0.0)
                + (monitor.counter_value('parallel/segment_cache_miss')
                   or 0.0))

    def calibrate(path, drift):
        # fit a comms model from REAL dispatch points: clean fabric ->
        # the stale pre-drift model; drift armed -> the tuned oracle
        comms.clear_dispatch_points()
        fluid.set_flags({'FLAGS_comms_model_path': os.devnull})
        if drift:
            faultinject.configure(drift_spec)
        try:
            main_p, startup, loss = build()
            with fluid.scope_guard(fluid.Scope()):
                exe = fluid.Executor(fluid.XLAPlace(0))
                exe.run(startup)
                for _ in range(6):
                    exe.run(main_p, feed=feed, fetch_list=[loss])
        finally:
            faultinject.reset()
        alpha, beta = comms.fit_linear(
            comms.dispatch_points('allreduce'))
        with open(path, 'w') as f:
            json.dump({'collectives': {'allreduce': {
                'latency_s': alpha, 'inv_bw_s_per_byte': beta}}}, f)
        comms.clear_dispatch_points()
        return {'latency_us': round(alpha * 1e6, 1),
                'inv_bw_s_per_byte': beta}

    arms = (('static_stale', stale_path, False),
            ('autopilot', stale_path, True),
            ('static_tuned', tuned_path, False))
    out = {'arms': {}}
    try:
        fluid.set_flags({'FLAGS_comms_plan': True,
                         'FLAGS_comms_bucket_bytes': 32 << 10,
                         'FLAGS_timeseries': True,
                         'FLAGS_autopilot': True,
                         'FLAGS_autopilot_interval_s': 0.05})
        out['stale_model'] = calibrate(stale_path, drift=False)
        out['tuned_model'] = calibrate(tuned_path, drift=True)

        setups = {}
        for name, mpath, _is_ap in arms:
            fluid.set_flags({'FLAGS_comms_model_path': mpath})
            main_p, startup, loss = build()
            scope = fluid.Scope()
            # one Executor PER ARM: parameter init folds the step
            # counter into its RNG (cross-arm loss parity)
            exe = fluid.Executor(fluid.XLAPlace(0))
            with fluid.scope_guard(scope):
                exe.run(startup)
                for _ in range(warmup):
                    exe.run(main_p, feed=feed, fetch_list=[loss])
            setups[name] = {'mpath': mpath, 'program': main_p,
                            'loss': loss, 'scope': scope, 'exe': exe,
                            'walls': [], 'pred': 0.0, 'meas': 0.0,
                            'steps': 0, 'final_loss': None}

        # ---- adaptation: drift on, autopilot arm alone, refit online
        faultinject.configure(drift_spec)
        fluid.set_flags({'FLAGS_comms_model_path': stale_path})
        autopilot.reset()
        autopilot.engage()
        refits0 = monitor.counter_value('autopilot/refits') or 0.0
        lowered0 = _lowered()
        s = setups['autopilot']
        pm0 = _pm()
        steps_to_refit = None
        pm_refit = None
        with fluid.scope_guard(s['scope']):
            for i in range(adapt_steps):
                s['exe'].run(s['program'], feed=feed,
                             fetch_list=[s['loss']])
                if steps_to_refit is None and \
                        (monitor.counter_value('autopilot/refits')
                         or 0.0) > refits0:
                    steps_to_refit = i + 1
                    pm_refit = _pm()
                elif steps_to_refit is not None and \
                        i + 1 >= steps_to_refit + 6:
                    break   # enough repriced post-refit samples
        out['adaptation'] = {
            'refits': int((monitor.counter_value('autopilot/refits')
                           or 0.0) - refits0),
            'steps_to_refit': steps_to_refit,
            'honesty_before_refit':
                _honesty(pm0, pm_refit) if pm_refit else None,
            'honesty_after_refit':
                _honesty(pm_refit, _pm()) if pm_refit else None,
            'retraces': int(_lowered() - lowered0),
        }
        autopilot.disengage()
        # stash the refit so it prices ONLY the autopilot arm's bursts
        with comms_plan._lock:
            ap_model = comms_plan._refit['pending'] or \
                comms_plan._refit['adopted']
        comms_plan.clear_refit()

        # ---- measurement: interleaved bursts under the same drift
        lowered_meas = _lowered()
        for _ in range(rounds):
            for name, mpath, is_ap in arms:
                s = setups[name]
                fluid.set_flags({'FLAGS_comms_model_path': mpath})
                if is_ap and ap_model:
                    comms_plan.install_refit(ap_model)
                pm_a = _pm()
                with fluid.scope_guard(s['scope']):
                    t0 = time.perf_counter()
                    for _ in range(per_round):
                        lv, = s['exe'].run(s['program'], feed=feed,
                                           fetch_list=[s['loss']])
                    s['walls'].append(time.perf_counter() - t0)
                pm_b = _pm()
                s['pred'] += pm_b[0] - pm_a[0]
                s['meas'] += pm_b[1] - pm_a[1]
                s['steps'] += per_round
                s['final_loss'] = float(np.asarray(lv))
                if is_ap:
                    comms_plan.clear_refit()
        out['post_warmup_retraces'] = int(_lowered() - lowered_meas)

        for name, _mpath, _is_ap in arms:
            s = setups[name]
            out['arms'][name] = {
                'steps_per_sec':
                    round(per_round / min(s['walls']), 2),
                'best_step_ms':
                    round(min(s['walls']) / per_round * 1e3, 3),
                'honesty':
                    _honesty((0.0, 0.0), (s['pred'], s['meas'])),
                'final_loss': s['final_loss'],
            }
        ap_h = out['arms']['autopilot']['honesty']
        tn_h = out['arms']['static_tuned']['honesty']
        tn_ms = out['arms']['static_tuned']['best_step_ms']
        ap_ms = out['arms']['autopilot']['best_step_ms']
        if ap_h is not None and tn_h is not None:
            out['autopilot_vs_tuned'] = {
                'honesty_gap': round(abs(ap_h - tn_h), 4),
                'step_delta_pct':
                    round(100.0 * (ap_ms - tn_ms) / tn_ms, 1),
            }
    finally:
        faultinject.reset()
        autopilot.disengage()
        comms_plan.clear_refit()
        fluid.set_flags(prev)
    return dict({'metric': 'autopilot_ab',
                 'value': out['arms'].get('autopilot', {}).get(
                     'honesty') or 0.0,
                 'unit': 'pred_over_measured'}, **out)


def bench_autoshard(batch=8, rounds=5, per_round=4, warmup=3):
    """Auto-sharding A/B (BENCH_autoshard.json): the SAME transformer
    block (qkv fc -> context-parallel attention -> proj -> MoE FFN,
    the test_sp_ep_fluid shape) trained three ways, interleaved so OS
    noise hits every arm equally —

      hand_spep:      the hand-placed dp2 x sp2 x ep2 mesh config
                      (FLAGS_auto_shard=0, the pre-planner posture),
      auto:           FLAGS_auto_shard=1 on the UNANNOTATED program
                      (no mesh, no rules, no axis names),
      auto_hbm_tight: same, under an injected HBM budget below the
                      fully-replicated residency, so the memviz gate
                      must REJECT at least one candidate layout before
                      anything compiles and the planner lands on a
                      scattered one.

    Per arm: best step wall, bytes-on-wire per step, attributed peak
    HBM, final loss (the parity claim rides in the artifact); the auto
    arms also embed their plan summary (chosen layout, candidate
    count, HBM rejections)."""
    return {'metric': 'autoshard_ab', 'unit': 'ms/step',
            'autoshard_ab': _autoshard_fields(batch, rounds,
                                              per_round, warmup)}


def _autoshard_fields(batch=8, rounds=5, per_round=4, warmup=3):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, memviz, monitor
    from paddle_tpu.parallel import mesh as pmesh
    from paddle_tpu.parallel import plan as auto_plan

    T, H, D, E, FF = 16, 4, 8, 4, 32
    DIM = H * D

    def build(seed=5):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[T, DIM], dtype='float32')
            y = layers.data('y', shape=[T, DIM], dtype='float32')
            qkv = layers.fc(x, size=3 * DIM, num_flatten_dims=2,
                            bias_attr=False)
            q, k, v = layers.split(qkv, 3, dim=-1)
            q = layers.reshape(q, [-1, T, H, D])
            k = layers.reshape(k, [-1, T, H, D])
            v = layers.reshape(v, [-1, T, H, D])
            att = layers.context_parallel_attention(q, k, v,
                                                    causal=True)
            att = layers.reshape(att, [-1, T, DIM])
            proj = layers.fc(att, size=DIM, num_flatten_dims=2,
                             bias_attr=False)
            h1 = layers.elementwise_add(x, proj)
            mo, aux = layers.moe(h1, num_experts=E, hidden_size=FF,
                                 aux_weight=0.01)
            out_v = layers.elementwise_add(h1, mo)
            mse = layers.reduce_mean(
                layers.square(layers.elementwise_sub(out_v, y)))
            loss = layers.elementwise_add(mse, aux)
            fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(batch, T, DIM).astype('float32'),
            'y': rng.randn(batch, T, DIM).astype('float32')}
    # the injected budget for the tight arm: below the fully-
    # replicated (dp-only) per-device residency, above the best
    # scattered candidate — the memviz gate must fire
    probe_main, _ps, _pl = build()
    free = auto_plan.build_plan(
        probe_main, ndev=8,
        feed_shapes={k: v.shape for k, v in feed.items()})
    repl_hbm = next(c['hbm_bytes'] for c in free.candidates
                    if tuple(c['layout']) == (8, 1, 1))
    auto_plan.reset()

    arms = (
        ('hand_spep', {'FLAGS_auto_shard': False,
                       'FLAGS_memviz_budget_bytes': 0}, True),
        ('auto', {'FLAGS_auto_shard': True,
                  'FLAGS_memviz_budget_bytes': 0}, False),
        ('auto_hbm_tight', {'FLAGS_auto_shard': True,
                            'FLAGS_memviz_budget_bytes':
                                repl_hbm * 0.8}, False),
    )
    prev = fluid.get_flags(['FLAGS_auto_shard',
                            'FLAGS_memviz_budget_bytes'])
    setups = {}
    out = {}
    try:
        for name, fl, hand_mesh in arms:
            fluid.set_flags(fl)
            main_p, startup, loss = build()
            comp = fluid.CompiledProgram(main_p).with_data_parallel(
                loss_name=loss.name)
            if hand_mesh:
                comp = comp.with_mesh(pmesh.create_mesh(dp=2, sp=2,
                                                        ep=2))
            scope = fluid.Scope()
            # one Executor per arm: parameter init folds the executor
            # step counter into its RNG (same rationale as
            # _plan_ab_fields)
            exe = fluid.Executor(fluid.XLAPlace(0))
            with fluid.scope_guard(scope):
                exe.run(startup)
                for _ in range(warmup):
                    exe.run(comp, feed=feed, fetch_list=[loss])
            setups[name] = {'flags': fl, 'comp': comp, 'loss': loss,
                            'scope': scope, 'exe': exe,
                            'program': main_p, 'walls': [],
                            'wire': 0.0, 'steps': 0,
                            'final_loss': None}
        for _ in range(rounds):
            for name, _fl, _hm in arms:
                s = setups[name]
                fluid.set_flags(s['flags'])
                with fluid.scope_guard(s['scope']):
                    w0 = monitor.counter_value('comms/bytes_on_wire')
                    t0 = time.perf_counter()
                    for _ in range(per_round):
                        lv, = s['exe'].run(s['comp'], feed=feed,
                                           fetch_list=[s['loss']])
                    s['walls'].append(time.perf_counter() - t0)
                    s['wire'] += monitor.counter_value(
                        'comms/bytes_on_wire') - w0
                    s['steps'] += per_round
                    s['final_loss'] = float(np.asarray(lv).ravel()[0])
        for name, s in setups.items():
            peak = memviz.peak_bytes(memviz.program_label(
                s['program']))
            row = {
                'best_step_ms': round(
                    min(s['walls']) / per_round * 1e3, 3),
                'steps_per_sec': round(per_round / min(s['walls']), 2),
                'bytes_on_wire_per_step':
                    round(s['wire'] / max(1, s['steps']), 1),
                'peak_hbm_bytes': peak,
                'final_loss': s['final_loss'],
            }
            ap = getattr(s['comp'], '_auto_plan', None)
            if ap is not None:
                row['plan'] = {
                    'layout': {'dp': ap.layout[0],
                               'fsdp': ap.layout[1],
                               'tp': ap.layout[2]},
                    'update_axis': ap.update_axis,
                    'candidates': len(ap.candidates),
                    'hbm_rejected': ap.rejected,
                    # the planner's own per-device residency estimate
                    # for the chosen layout (the quantity the memviz
                    # gate compared against the budget)
                    'est_hbm_bytes': round(ap.chosen['hbm_bytes'], 1),
                    'digest': ap.digest(),
                }
            out[name] = row
        tight = out.get('auto_hbm_tight', {}).get('plan', {})
        out['hbm_gate_fired'] = bool(tight.get('hbm_rejected'))
        hand = out.get('hand_spep', {})
        auto = out.get('auto', {})
        if hand.get('best_step_ms') and auto.get('best_step_ms'):
            out['auto_vs_hand_step_delta_pct'] = round(
                100.0 * (auto['best_step_ms'] - hand['best_step_ms'])
                / hand['best_step_ms'], 1)
    finally:
        fluid.set_flags(prev)
    return out


def _skew_job_fields(run_for):
    """The cross-rank half of bench_parallel: a real two-subprocess
    job (tests/comms_worker.py, rank 1 with a 4x batch), scraped for
    the aggregator's skew report and merged through collect_job.
    Degrades to {'skew': None} if the job cannot come up — the
    in-process comms numbers must survive a constrained container."""
    import socket
    import subprocess
    import urllib.request

    def free_port():
        s = socket.socket()
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def get(url, timeout=5):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()

    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, 'tests', 'comms_worker.py')
    p0, p1 = free_port(), free_port()
    spec = '0=127.0.0.1:%d,1=127.0.0.1:%d' % (p0, p1)
    base = dict(os.environ,
                PADDLE_TPU_STATUS_WORKERS=spec,
                FLAGS_health_heartbeat_seconds='0.5',
                FLAGS_trace='1')
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p1), str(run_for + 60), '4'],
            env=dict(base, PADDLE_TRAINER_ID='1',
                     PADDLE_TPU_STATUS_AGGREGATE='0'),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p0), str(run_for + 60)],
            env=dict(base, PADDLE_TRAINER_ID='0',
                     PADDLE_TPU_STATUS_AGGREGATE='1'),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        agg = 'http://127.0.0.1:%d' % p0
        deadline = time.time() + run_for + 90
        skew = None
        while time.time() < deadline:
            try:
                code, body = get(agg + '/statusz')
                doc = json.loads(body)
                job = doc.get('job') or {}
                skew = job.get('skew')
                workers = job.get('workers') or {}
                if skew and len(workers) >= 2 and \
                        all(w.get('up') for w in workers.values()):
                    break
            except Exception:
                pass
            time.sleep(1.0)
        merged = None
        try:
            code, body = get(agg + '/trace/collect', timeout=30)
            doc = json.loads(body)
            merged = {
                'ranks': len(doc['ptJob']['workers']),
                'events': sum(1 for e in doc['traceEvents']
                              if e.get('ph') == 'X'),
                'skipped': len(doc['ptJob']['skipped']),
            }
        except Exception:
            pass
        out = {'skew': None, 'job_timeline': merged}
        if skew:
            wall = skew['wall']
            worst_phase = None
            if skew.get('phases'):
                name, ph = max(skew['phases'].items(),
                               key=lambda kv: kv[1]['ratio'])
                worst_phase = {'phase': name,
                               'slowest_rank': ph['slowest_rank'],
                               'ratio': round(ph['ratio'], 3)}
            out['skew'] = {
                'slowest_rank': wall['slowest_rank'],
                'skew_ratio': round(wall['skew_ratio'], 3),
                'max_p50_ms': round(wall['max_p50_ms'], 3),
                'median_p50_ms': round(wall['median_p50_ms'], 3),
                'worst_phase': worst_phase,
            }
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


SMOKE_BENCHES = (('dispatch', {}),
                 ('health_overhead', {}),
                 ('memviz_overhead', {}),
                 ('opprof_overhead', {}),
                 ('lenet', {'batch': 64, 'steps': 30}))


# --all entries: (name, config variants tried in order).  The second
# variant is a near-equivalent config with a DIFFERENT XLA program
# fingerprint — observed failure mode on the tunnel service: one
# poisoned fingerprint hangs its compile RPC forever while every other
# program is fine, so a one-off variant recovers the metric.
ALL_BENCHES = (
    # lenet fallback chain: the wedged compile (multi-pass dW conv,
    # tools/repro_conv_wedge.py) is dodged FIRST by downgrading the
    # conv algorithm at the same batch, THEN by the old b500 swap
    ('lenet', ({}, {'conv_precision': 'default'}, {'batch': 500})),
    ('bert', ({},)),
    ('bert_long', ({},)),
    ('bert_long_dropout', ({},)),
    ('wide_deep', ({}, {'batch': 2000})),
    ('wide_deep_sparse', ({},)),
    ('host_sparse_push', ({},)),
    ('rpc_sparse_push', ({},)),
    ('transformer', ({},)),
    ('resnet_infer', ({}, {'batch': 64})),
    ('resnet50_hostfed', ({},)),
    ('serving', ({},)),
)


def _run_entry(name, kwargs, timeout=900):
    """Run one bench entry in a child process under a deadline and
    print its JSON line.  A wedged device RPC (the tunnel compile
    service can hang on one program fingerprint) costs this attempt,
    not the whole sweep.  Returns True on success."""
    import subprocess
    try:
        p = subprocess.run(
            [sys.executable, '-u', os.path.abspath(__file__),
             '--one', name, json.dumps(kwargs)],
            capture_output=True, text=True, timeout=timeout)
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith('{')]
        if line:
            # accept the metric even on a nonzero exit: a measured
            # JSON line followed by a teardown crash is still a result
            print(line[-1])
            return True
        sys.stderr.write('%s %s failed (rc=%d): %s\n'
                         % (name, kwargs or '', p.returncode,
                            p.stderr[-300:]))
    except subprocess.TimeoutExpired:
        sys.stderr.write('%s %s timed out after %ds (wedged device '
                         'RPC?)\n' % (name, kwargs or '', timeout))
    return False


def main():
    if len(sys.argv) > 1 and sys.argv[1] in ('--parallel',
                                             '--auto-shard',
                                             '--autopilot'):
        # multi-device posture BEFORE the first jax import: the comms
        # and placement numbers need a real mesh (8 virtual CPU
        # devices when the host has no accelerator platform
        # configured)
        flags = os.environ.get('XLA_FLAGS', '')
        if 'xla_force_host_platform_device_count' not in flags:
            os.environ['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=8'
            ).strip()
    _enable_compile_cache()
    if len(sys.argv) > 1 and sys.argv[1] == '--one' and \
            len(sys.argv) < 3:
        sys.stderr.write('usage: bench.py --one NAME [kwargs-json]\n')
        sys.exit(2)
    if len(sys.argv) > 2 and sys.argv[1] == '--one':
        kwargs = json.loads(sys.argv[3]) if len(sys.argv) > 3 else {}
        if sys.argv[2] == 'resnet50':
            ips = bench_resnet50(**kwargs)
            rec = dict({
                'metric': 'resnet50_train_images_per_sec_chip',
                'value': round(ips, 2), 'unit': 'images/sec',
                'vs_baseline': round(ips / 365.0, 3)},
                **LAST_PERF, **_step_phase_fields(),
                **_monitor_fields())
        else:
            rec = globals()['bench_' + sys.argv[2]](**kwargs)
        print(json.dumps(rec))
        # every entry (--one is also how --all/--cold/--elastic spawn
        # children) lands one line in the run-to-run history
        if isinstance(rec, dict):
            append_history(sys.argv[2], rec)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--cold':
        # process-restart latency: cold (populate the persistent
        # compile cache) vs warm (start from it) vs warm+warmup.
        # Baseline recorded in BENCH_compile_cache.json.
        out = sys.argv[2] if len(sys.argv) > 2 else None
        _run_cold(out_path=out)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--elastic':
        # elastic reconfiguration: save under dp2, resume on a
        # different topology cold vs warm through the persistent
        # compile cache, reshard predicted-vs-measured, checkpoint
        # save/load bandwidth.  Baseline recorded in
        # BENCH_elastic.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_elastic.json')
        _run_elastic(out_path=out)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--chaos':
        # self-healing chaos soak: real multi-process job, >= 4
        # injected fault kinds, zero-intervention completion with
        # bounded lost work and bitwise post-recovery verification.
        # Baseline recorded in BENCH_chaos.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_chaos.json')
        rec = bench_chaos()
        print(json.dumps(rec))
        append_history('chaos', rec)
        with open(out, 'w') as f:
            json.dump({'cmd': 'JAX_PLATFORMS=cpu python bench.py '
                              '--chaos',
                       'entries': [rec]}, f, indent=1, sort_keys=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--serving':
        # multi-client serving soak (continuous batching vs
        # sequential single requests).  Baseline recorded in
        # BENCH_serving.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_serving.json')
        rec = bench_serving()
        print(json.dumps(rec))
        append_history('serving_soak', rec)
        with open(out, 'w') as f:
            json.dump({'cmd': 'JAX_PLATFORMS=cpu python bench.py '
                              '--serving',
                       'entries': [rec]}, f, indent=1, sort_keys=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--serving-fleet':
        # skewed-tenant churn soak: two-replica fleet (priced
        # migrations, p99 held) vs one replica eating the re-warm
        # wall on the serving path.  Baseline recorded in
        # BENCH_fleet.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_fleet.json')
        rec = bench_serving_fleet()
        print(json.dumps(rec))
        append_history('serving_fleet', rec)
        with open(out, 'w') as f:
            json.dump({'cmd': 'JAX_PLATFORMS=cpu python bench.py '
                              '--serving-fleet',
                       'entries': [rec]}, f, indent=1, sort_keys=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--kernels':
        # pallas kernel library A/B: shipped auto-dispatch vs the
        # dense reference per kernel, interleaved, dispatch counters
        # + zero-post-warmup-retrace proof in the artifact.  Baseline
        # recorded in BENCH_kernels.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_kernels.json')
        rec = bench_kernels()
        print(json.dumps(rec))
        append_history('kernels', rec)
        with open(out, 'w') as f:
            json.dump({'cmd': 'JAX_PLATFORMS=cpu python bench.py '
                              '--kernels',
                       'entries': [rec]}, f, indent=1, sort_keys=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--auto-shard':
        # auto-sharding planner A/B: FLAGS_auto_shard=1 on an
        # unannotated program vs the hand-placed sp/ep mesh config,
        # interleaved, with an HBM-gate rejection arm.  Baseline
        # recorded in BENCH_autoshard.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_autoshard.json')
        rec = bench_autoshard()
        print(json.dumps(rec))
        append_history('autoshard', rec)
        with open(out, 'w') as f:
            json.dump({'cmd': 'JAX_PLATFORMS=cpu python bench.py '
                              '--auto-shard',
                       'entries': [rec]}, f, indent=1, sort_keys=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--autopilot':
        # closed-loop autopilot A/B: stale static comms model vs
        # online-refitting autopilot vs drift-calibrated hand-tuned
        # reference, all under the same injected fabric drift.
        # Baseline recorded in BENCH_autopilot.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_autopilot.json')
        rec = bench_autopilot()
        print(json.dumps(rec))
        append_history('autopilot', rec)
        with open(out, 'w') as f:
            json.dump({'cmd': 'JAX_PLATFORMS=cpu python bench.py '
                              '--autopilot',
                       'entries': [rec]}, f, indent=1, sort_keys=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--parallel':
        # collective-job comms telemetry: bytes on wire, achieved
        # bandwidth per (collective, size bucket), cross-rank skew.
        # Baseline recorded in BENCH_comms.json.
        out = sys.argv[2] if len(sys.argv) > 2 else \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'BENCH_comms.json')
        rec = bench_parallel()
        print(json.dumps(rec))
        append_history('parallel', rec)
        with open(out, 'w') as f:
            json.dump({'cmd': 'JAX_PLATFORMS=cpu python bench.py '
                              '--parallel',
                       'entries': [rec]}, f, indent=1, sort_keys=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--smoke':
        # CPU-friendly minutes-scale sweep: the dispatch micro-bench
        # (steady-state host time per step — the fast-path metric) and
        # a small LeNet entry, each in its own child process so the
        # monitor registry is per-entry.  Baseline recorded in
        # BENCH_fastpath_smoke.json.
        for name, kwargs in SMOKE_BENCHES:
            _run_entry(name, kwargs, timeout=600)
        return
    if len(sys.argv) > 1 and sys.argv[1] == '--all':
        # secondary configs (BASELINE.json 0,2,3,4); the driver contract
        # stays the default single-line ResNet metric
        for name, variants in ALL_BENCHES:
            for kwargs in variants:
                if _run_entry(name, kwargs):
                    break
        return
    # NHWC is the TPU-native conv layout (channels on the 128-lane
    # minor dim) and measures ~8% faster than NCHW here
    layout = os.environ.get('PADDLE_TPU_BENCH_LAYOUT', 'NHWC')
    for batch in (128, 64, 32):
        if _run_entry('resnet50',
                      {'batch': batch, 'data_format': layout}):
            return
    print(json.dumps({'metric': 'resnet50_train_images_per_sec_chip',
                      'value': 0.0, 'unit': 'images/sec',
                      'vs_baseline': 0.0}))


if __name__ == '__main__':
    main()
