# Top-level build for paddle_tpu's native artifacts + package checks.
# Reference analog: the cmake tree (CMakeLists.txt + cmake/) that builds
# libpaddle_framework / capi / train demo.  Here the native surface is
# three artifacts:
#
#   paddle_tpu/runtime/libptruntime.so      multithreaded datafeed + PS
#   paddle_tpu/inference/capi/libpaddle_tpu_capi.so   stable C API
#   build/demo_trainer                      C++ training entry demo
#
# `make` builds all three; `make test` runs the suite on the 8-device
# virtual CPU mesh; `make wheel` packages the python tree + built .so
# files with setup.py.

CXX ?= g++
CXXFLAGS ?= -O2 -std=c++17 -fPIC -pthread -Wall

NATIVE := paddle_tpu/runtime/libptruntime.so \
          paddle_tpu/inference/capi/libpaddle_tpu_capi.so \
          build/demo_trainer

all: $(NATIVE)

paddle_tpu/runtime/libptruntime.so: \
		paddle_tpu/runtime/datafeed.cc \
		paddle_tpu/runtime/ps_service.cc
	$(MAKE) -C paddle_tpu/runtime

paddle_tpu/inference/capi/libpaddle_tpu_capi.so: \
		paddle_tpu/inference/capi/c_api.cc \
		paddle_tpu/inference/capi/c_api.h
	$(MAKE) -C paddle_tpu/inference/capi

build/demo_trainer: paddle_tpu/train/demo/demo_trainer.cc \
		paddle_tpu/inference/capi/libpaddle_tpu_capi.so
	mkdir -p build
	$(CXX) $(CXXFLAGS) -Ipaddle_tpu/inference/capi -o $@ $< \
	  -Lpaddle_tpu/inference/capi -lpaddle_tpu_capi \
	  -Wl,-rpath,'$$ORIGIN/../paddle_tpu/inference/capi'

test: all
	JAX_PLATFORMS=cpu \
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python -m pytest tests/ -q

bench:
	python bench.py

# gates: the monitor instrument points the observability contract
# depends on must stay in the source, the steady-state step fast
# path must stay within its per-step counter budgets, the persistent
# compile cache must carry executables across processes, the trace
# plane must decompose a real step (merged host+device export,
# >=80% phase coverage) without costing anything when disabled, the
# health plane must serve lint-clean /metrics + schema-stable
# /healthz//statusz off a live executor with zero hot-path cost when
# tensor-health summaries are off, the serving plane must batch
# a real two-thread soak bitwise-correctly with zero post-warmup
# retraces and lint-clean serving metrics, and the job-wide
# observability plane must merge a real two-process job into one
# schema-valid per-rank timeline with nonzero collective telemetry
# and a calibrated comms cost model within 2x of measured, and the
# device-memory plane must attribute per-(program, segment) peaks,
# sample the live-HBM census into gauges + a Perfetto counter track,
# and cost nothing when off, and the auto-sharding planner must plan
# a real two-process job on every rank (parallel/plan_* counters +
# /statusz auto_shard) while FLAGS_auto_shard=0 stays bit-for-bit
# the hand-placed behavior, and the elastic resilience plane must
# survive a real kill -9 mid-save (last-good generation loadable,
# torn shards refused by name) and resume a checkpoint across
# process and layout changes at loss parity with zero post-warmup
# retraces
# and the static program verifier must catch every seeded defect
# class by name in a real executor run while the tier-1 model corpus
# verifies clean and the disabled path stays within the hot-path
# budgets, and the repo must hold its flag-hygiene and
# lock-discipline lints, and the self-healing supervisor must confirm
# a real kill -9 through the aggregator and degrade to the survivor
# inside the rejoin budget at bitwise loss parity, and the chaos soak
# must drive >= 4 injected fault kinds (worker kill, torn shard, rpc
# fault, heartbeat flap, collective stall) to zero-intervention
# completion with bounded lost work and every fault matched to a
# named supervisor decision in /statusz, and the time-series telemetry
# plane must serve schema-valid /timeseries windows (per-worker AND
# aggregated on a real two-process job), fire a deliberately-tight SLO
# at /alertz with the breaching series cited in the supervisor
# decision log, hold the hot-path budgets with sampling off, and the
# run-to-run regression gate must pass an honest rerun while failing
# a seeded faultinject slowdown by name, and the pallas kernel library
# must hold the auto-dispatch + dense-fallback contract (documented
# fallback per kernel, forced-fused-vs-dense parity on CPU, dispatch
# counters + /statusz reasons, FLAGS_pallas_* knobs wired), and the
# closed-loop autopilot must refit a deliberately-dishonest comms
# model from live dispatch points with zero retrace churn (digest
# moves only at adoption), freeze to bit-identical knobs under
# FLAGS_autopilot=0 and restore the static plan in one revert; the
# serving fleet must route a skewed-tenant soak across two live
# replicas sticky and retrace-free, land a priced migration bitwise-
# equal, surface its decisions over HTTP, and cost one weak-set read
# when no fleet exists; and the op-cost attribution plane must replay
# a warmed LeNet into per-instance rows whose segment sums agree with
# the step report's dispatch wall within 10%, emit a schema-valid
# op_worklist.json naming >= 3 ranked candidates with the warmed adam
# run cross-referenced to pallas/fused_optimizer, serve /statusz
# op_costs + /opprof live, and cost one flag read per step when off
check:
	python tools/check_stat_coverage.py
	python tools/staticcheck.py
	JAX_PLATFORMS=cpu python tools/check_progcheck.py
	JAX_PLATFORMS=cpu python tools/check_hot_path.py
	JAX_PLATFORMS=cpu python tools/check_compile_cache.py
	JAX_PLATFORMS=cpu python tools/check_trace.py
	JAX_PLATFORMS=cpu python tools/check_health.py
	JAX_PLATFORMS=cpu python tools/check_serving.py
	JAX_PLATFORMS=cpu python tools/check_comms.py
	JAX_PLATFORMS=cpu python tools/check_memviz.py
	JAX_PLATFORMS=cpu python tools/check_opprof.py
	JAX_PLATFORMS=cpu python tools/check_autoshard.py
	JAX_PLATFORMS=cpu python tools/check_elastic.py
	JAX_PLATFORMS=cpu python tools/check_supervisor.py
	JAX_PLATFORMS=cpu python tools/check_chaos.py
	JAX_PLATFORMS=cpu python tools/check_timeseries.py
	JAX_PLATFORMS=cpu python tools/check_kernels.py
	JAX_PLATFORMS=cpu python tools/check_autopilot.py
	JAX_PLATFORMS=cpu python tools/check_fleet.py
	JAX_PLATFORMS=cpu python tools/check_regress.py --selftest

wheel: all
	python setup.py bdist_wheel 2>/dev/null || python setup.py sdist

clean:
	$(MAKE) -C paddle_tpu/runtime clean 2>/dev/null || true
	$(MAKE) -C paddle_tpu/inference/capi clean
	rm -rf build dist *.egg-info

.PHONY: all test bench check wheel clean
