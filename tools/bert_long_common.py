"""Shared BERT-long benchmark program builder for the ceiling-diff
tools (diff_bert_long, dump_bert_long_hlo, profile_bert_long_pair,
boundary_cost): ONE definition of the model/optimizer/seed so every
tool compares the exact same program."""

import numpy as np


def build_bert_long_program(batch, seq):
    """Returns (main, startup, loss, batch_data) — the bench_bert_long
    configuration: BERT-base, attn_dropout=0 (flash path), bf16 AMP +
    dynamic loss scaling, Adam, seed 42, device-resident feeds."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    cfg = models.bert.BertConfig(max_pos=seq, attn_dropout=0.0)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, enc, loss = models.bert.build_pretrain(cfg, seq)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Adam(1e-4), use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    batch_data = models.bert.synthetic_batch(cfg, batch, seq, rng)
    batch_data = {k: jax.device_put(v) for k, v in batch_data.items()}
    return main, startup, loss, batch_data


def build_train_segment(batch, seq, fetch=()):
    """Shared segment plumbing for the diagnostic tools: build the
    program, run startup, extract the (single) device train segment,
    and assemble its state/data dicts the way the executor's run path
    does.  Returns a dict with main/startup/loss/batch_data/scope/exe/
    seg/fn (unjitted segment callable)/state/data/out_state_names."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.executor import _Segment, _make_segment_fn
    from paddle_tpu.fluid import core
    main, startup, loss, batch_data = build_bert_long_program(batch, seq)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        plan = exe._build_plan(main, tuple(sorted(batch_data.keys())),
                               tuple(fetch))
        segs = [it for it in plan if isinstance(it, _Segment)]
        assert len(segs) == 1, [len(s.ops) for s in segs]
        seg = segs[0]
        state = {n: core.as_array(scope.find_var(n))
                 for n in seg.state_names}
        data = {n: batch_data.get(
                    n, scope.find_var(n) and
                    core.as_array(scope.find_var(n)))
                for n in seg.input_names}
    return {'main': main, 'startup': startup, 'loss': loss,
            'batch_data': batch_data, 'scope': scope, 'exe': exe,
            'seg': seg, 'fn': _make_segment_fn(seg, seg.prefer_test),
            'state': state, 'data': data,
            'out_state_names': [n for n in seg.output_names
                                if n in state]}
