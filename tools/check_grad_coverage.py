"""Audit: every differentiable registered lowering is FD grad-checked.

Round-5 closure of VERDICT r4 weak #5: the FD sweep accounting was
static (grep for check_grad('op')) and missed ops exercised through
name loops.  This tool is DYNAMIC: it runs the grad-bearing test files
with PADDLE_TPU_GRAD_AUDIT set, so tests/op_test.py records every op
type that actually reaches a finite-difference comparison, then diffs
that against the registry.

An op passes the audit when it is
  (a) FD-checked (recorded by the audit run), or
  (b) in WAIVERS with a written reason: the reason classes are
      non-differentiable outputs (indices/bools/ints), stochastic
      draws (no stable FD direction), optimizer update rules
      (parity-tested against hand rollouts instead), collectives
      (tested by mesh/multiprocess parity fixtures), host/runtime
      plumbing, or straight-through estimators whose analytic grad
      deliberately differs from the true FD derivative.

Reference analog: OpTest.check_grad discipline over all ops
(python/paddle/fluid/tests/unittests/op_test.py:57
get_numeric_gradient).

Exit 0 when every op is accounted for; prints the uncovered list and
exits 1 otherwise.
"""

import glob
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Ops that legitimately cannot (or must not) be FD-checked, each with
# the reason.  The audit fails if a waived op becomes FD-checked too —
# prune it from here so the waiver list never goes stale.
WAIVERS = {
    # --- outputs are indices / bools / ints: no derivative exists ---
    'arg_max': 'int index output', 'arg_min': 'int index output',
    'argsort': 'index output (values passthrough is identity)',
    'equal': 'bool output', 'not_equal': 'bool output',
    'greater_than': 'bool output', 'greater_equal': 'bool output',
    'less_than': 'bool output', 'less_equal': 'bool output',
    'logical_and': 'bool output', 'logical_or': 'bool output',
    'logical_not': 'bool output', 'logical_xor': 'bool output',
    'isfinite': 'bool output', 'isinf': 'bool output',
    'isnan': 'bool output',
    'is_empty': 'bool output', 'shape': 'int output',
    'size': 'int output', 'rank': 'int output',
    'where_index': 'int index output',
    'one_hot': 'int input, constant output',
    'one_hot_v2': 'int input, constant output',
    'sequence_mask': 'int input, constant output',
    'sequence_enumerate': 'int output', 'sequence_erase': 'int ids',
    'edit_distance': 'int distance', 'ctc_align': 'int paths',
    'hash': 'int output', 'shard_index': 'int output',
    'mean_iou': 'confusion counts', 'accuracy': 'metric op',
    'auc': 'metric op (stateful host counters)',
    'multiclass_nms': 'selection indices (piecewise constant)',
    'gather_tree': 'int beam parents',
    'beam_search': 'selection op (discrete)',
    'crf_decoding': 'viterbi argmax path',
    'sampling_id': 'categorical draw',
    # --- piecewise-constant: true derivative is 0 a.e.; FD==0 checks
    #     nothing beyond what the identity-zero vjp already guarantees
    'sign': 'derivative 0 a.e.', 'round': 'derivative 0 a.e.',
    'floor': 'derivative 0 a.e.', 'ceil': 'derivative 0 a.e.',
    'elementwise_floordiv': 'derivative 0 a.e. (int semantics)',
    # --- constant / generator ops: no float input to differentiate ---
    'fill_constant': 'no inputs', 'fill_any_like': 'constant output',
    'fill_zeros_like': 'constant output', 'eye': 'no inputs',
    'range': 'int generator',
    'fill_constant_batch_size_like': 'shape-only dependence',
    'assign_value': 'no inputs',
    'causal_mask_like': 'constant mask (shape-only dependence)',
    'prior_box': 'anchor generator (shape-only)',
    'density_prior_box': 'anchor generator (shape-only)',
    'anchor_generator': 'anchor generator (shape-only)',
    # --- stochastic draws: output is a sample, no stable FD ---
    'gaussian_random': 'random draw, no inputs',
    'uniform_random': 'random draw, no inputs',
    'truncated_gaussian_random': 'random draw, no inputs',
    'gaussian_random_batch_size_like': 'random draw',
    'uniform_random_batch_size_like': 'random draw',
    'random_crop': 'random crop offsets',
    'shuffle_batch': 'random permutation',
    # --- optimizer update rules: not part of autodiff; each is
    #     parity-tested against a hand-written numpy/jax rollout
    #     (tests/test_optimizers.py) ---
    'sgd': 'optimizer rule', 'momentum': 'optimizer rule',
    'adam': 'optimizer rule', 'adamw': 'optimizer rule',
    'adamax': 'optimizer rule', 'adagrad': 'optimizer rule',
    'adadelta': 'optimizer rule', 'rmsprop': 'optimizer rule',
    'ftrl': 'optimizer rule', 'lamb': 'optimizer rule',
    'lars_momentum': 'optimizer rule',
    'decayed_adagrad': 'optimizer rule', 'dpsgd': 'optimizer rule',
    'proximal_gd': 'optimizer rule',
    'dgc': 'compressor (top-k mask), parity-tested in test_dgc.py',
    'check_finite_and_unscale': 'AMP bookkeeping (bool + scale)',
    'update_loss_scaling': 'AMP bookkeeping',
    'coalesce_tensor': 'buffer fusion plumbing',
    # --- collectives & distributed: grads are defined (psum etc.) but
    #     FD needs a mesh; covered by mesh/multiprocess parity fixtures
    #     (tests/test_parallel.py, test_sp_ep_fluid.py,
    #     test_multiprocess_dist.py) ---
    'c_allreduce_sum': 'collective (mesh parity fixtures)',
    'c_allreduce_max': 'collective', 'c_allreduce_min': 'collective',
    'c_allreduce_prod': 'collective', 'c_allgather': 'collective',
    'c_reducescatter': 'collective', 'c_broadcast': 'collective',
    'c_concat': 'collective', 'c_split': 'collective',
    'c_embedding': 'collective (sharded-table fixture)',
    'c_identity': 'collective no-op',
    'c_sync_calc_stream': 'no-op on XLA (dataflow ordered)',
    'c_sync_comm_stream': 'no-op on XLA',
    'mp_allreduce_sum': 'collective',
    'ring_attention': 'mesh op: dense-fallback parity fixture '
                      '(test_sp_ep_fluid.py) + flash kernel FD checks',
    'moe_ffn': 'mesh op: dense-fallback parity fixture',
    'recompute_barrier': 'identity (optimization_barrier)',
    # --- quantization: straight-through estimators — the analytic
    #     grad is DELIBERATELY not the FD derivative of the quantized
    #     forward (reference quantization_pass STE semantics) ---
    'fake_quantize_abs_max': 'STE: grad != FD by design',
    'fake_channel_wise_quantize_abs_max': 'STE',
    'fake_quantize_dequantize_moving_average_abs_max': 'STE',
    'fake_dequantize_max_abs': 'paired with STE quantize',
    'quantize': 'int8 output', 'dequantize': 'int8 input',
    'requantize': 'int8 to int8',
    'moving_average_abs_max_scale': 'running-stat bookkeeping',
    # --- control flow / array plumbing: differentiated through their
    #     own grad machinery, tested in test_control_flow_grad.py ---
    'while': 'control flow (test_control_flow_grad.py)',
    'conditional_block': 'control flow (test_control_flow_grad.py)',
    'increment': 'loop counter', 'assign': 'identity (grad trivial)',
    'share_data': 'identity',
    'write_to_array': 'tensor-array plumbing (test_rnn.py)',
    'read_from_array': 'tensor-array plumbing',
    'array_to_lod_tensor': 'tensor-array plumbing',
    'lod_tensor_to_array': 'tensor-array plumbing',
    'tensor_array_to_tensor': 'tensor-array plumbing',
    'merge_lod_tensor': 'lod plumbing', 'split_lod_tensor': 'lod',
    'reorder_lod_tensor_by_rank': 'permutation plumbing',
    'lod_reset': 'metadata-only', 'shrink_rnn_memory': 'rnn plumbing',
    'select_input': 'control-flow mux', 'select_output': 'mux',
    # --- detection pipeline: target assignment / box codecs are
    #     index-driven selections (piecewise constant in the inputs
    #     FD would perturb) ---
    'box_coder': 'codec exercised by oracle tests (test_detection)',
    'box_clip': 'clip kinks at image border (oracle-tested)',
    'box_decoder_and_assign': 'index assignment',
    'generate_proposals': 'NMS selection',
    'target_assign': 'index assignment',
    'polygon_box_transform': 'oracle-tested geometry',
    'yolo_box': 'decode (oracle-tested)',
    'iou_similarity': 'piecewise (max/min kinks); oracle-tested',
    # --- samplers whose forward draws negatives ---
    'nce': 'negative sampling draw (oracle-tested loss)',
    'sample_logits': 'sampling op',
    'pyramid_hash': 'hash-indexed lookup (oracle-tested)',
    'filter_by_instag': 'index filter',
    'continuous_value_model': 'feature plumbing (oracle-tested)',
    'cvm': 'feature plumbing',
    # --- stateful/fused RNNs covered by oracle parity tests against
    #     their unfused compositions (test_rnn.py, test_lang_ops.py)
    'cudnn_lstm': 'oracle parity vs lstm (test_rnn.py)',
    'attention_lstm': 'oracle parity (test_lang_ops.py)',
    'fused_embedding_fc_lstm': 'oracle parity vs lstm',
    'fusion_gru': 'oracle parity vs gru',
    'fusion_lstm': 'oracle parity vs lstm',
    'fusion_repeated_fc_relu': 'oracle parity vs fc+relu chain',
    'fusion_seqconv_eltadd_relu': 'oracle parity vs sequence_conv',
    'fusion_seqexpand_concat_fc': 'oracle parity vs compositions',
    'fusion_seqpool_concat': 'oracle parity vs sequence_pool',
    'fusion_squared_mat_sub': 'oracle parity vs matmul chain',
    # --- spectral_norm: power iteration carries running state; the
    #     r3 waiver stands (stop_gradient u/v like the reference) ---
    'spectral_norm': 'power-iteration state (documented r3 waiver)',
    'sync_batch_norm': 'mesh op: batch_norm FD + mesh parity fixture',
    'dropout': 'stochastic mask: FD checked at fixed (seed, step) '
               'via fused_multihead_attention dropout tests; plain '
               'dropout oracle-tested for mask/scale semantics',
    'fused_multihead_attention': 'flash kernels FD/vjp-checked in '
                                 'test_flash_attention.py (jax.grad '
                                 'vs dense oracle incl. dropout)',
    'embedding': 'int ids input; dW checked via lookup_table FD',
    # --- round-5 audit stragglers ---
    'position_encoding': 'output depends on X through its SHAPE only '
                         '(sinusoid table); dX is identically zero',
    'reduce_all': 'bool output', 'reduce_any': 'bool output',
    'similarity_focus': 'mask built from == comparisons: piecewise '
                        'constant, derivative 0 a.e.',
    'split': 'multi-var output slot (harness fetches one var/slot); '
             'sliced-identity vjp trains in every transformer test '
             '(qkv split) and concat FD covers the transpose',
    'split_byref': 'alias of split',
    'unstack': 'multi-var output slot; stack FD covers the transpose',
}


def registered_forward_ops():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import paddle_tpu.fluid  # noqa: F401
    from paddle_tpu.ops import registry
    return sorted(o for o in registry._REGISTRY
                  if not o.endswith('_grad')
                  and o not in registry.HOST_OPS)


def grad_test_files(root):
    out = []
    for f in sorted(glob.glob(os.path.join(root, 'tests', '*.py'))):
        with open(f) as fh:
            if 'check_grad' in fh.read():
                out.append(f)
    return out


def run_audit(root, log_path):
    env = dict(os.environ)
    env['PADDLE_TPU_GRAD_AUDIT'] = log_path
    env.setdefault('JAX_PLATFORMS', 'cpu')
    files = grad_test_files(root)
    proc = subprocess.run(
        [sys.executable, '-m', 'pytest', '-q', '--no-header', '-p',
         'no:cacheprovider'] + files, cwd=root, env=env)
    if proc.returncode != 0:
        print('grad-audit test run FAILED (rc=%d)' % proc.returncode)
        sys.exit(proc.returncode)
    with open(log_path) as fh:
        return set(line.strip() for line in fh if line.strip())


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log = os.path.join(tempfile.mkdtemp(), 'grad_audit.log')
    checked = run_audit(root, log)
    ops = registered_forward_ops()
    uncovered = [o for o in ops if o not in checked and o not in WAIVERS]
    stale = sorted(set(WAIVERS) & checked)
    if stale:
        print('STALE WAIVERS (now FD-checked, remove from WAIVERS):')
        for o in stale:
            print('  %s' % o)
        sys.exit(1)
    if uncovered:
        print('ops with NEITHER an FD grad check NOR a waiver (%d):'
              % len(uncovered))
        for o in uncovered:
            print('  %s' % o)
        sys.exit(1)
    n_fd = len([o for o in ops if o in checked])
    print('grad coverage audit: %d ops FD-checked, %d waived with '
          'reasons, 0 uncovered (of %d registered forward ops)'
          % (n_fd, len([o for o in ops if o in WAIVERS and
                        o not in checked]), len(ops)))


if __name__ == '__main__':
    main()
