"""API-surface audit vs the reference's public Python API (the
tools/diff_api.py / check_api_approvals.sh analog).

Collects __all__ exports from the reference's python/paddle/fluid
modules (static parse — the reference needs its compiled core to
import) and checks each against our paddle_tpu.fluid namespace.
"""

import ast
import os
import sys
import warnings

warnings.filterwarnings('ignore', category=SyntaxWarning)

REFERENCE = os.environ.get('PADDLE_REFERENCE', '/root/reference')
REF_PY = os.path.join(REFERENCE, 'python/paddle/fluid')

# reference module -> our attribute path under paddle_tpu.fluid
MODULES = {
    'layers/nn.py': 'layers',
    'layers/tensor.py': 'layers',
    'layers/control_flow.py': 'layers',
    'layers/loss.py': 'layers',
    'layers/detection.py': 'layers',
    'layers/sequence_lod.py': 'layers',
    'layers/learning_rate_scheduler.py': 'layers',
    'layers/ops.py': 'layers',
    'layers/io.py': 'layers',
    'layers/rnn.py': 'layers',
    'layers/distributions.py': 'layers',
    'layers/metric_op.py': 'layers',
    'layers/device.py': 'layers',
    'optimizer.py': 'optimizer',
    'initializer.py': 'initializer',
    'regularizer.py': 'regularizer',
    'clip.py': 'clip',
    'metrics.py': 'metrics',
    'io.py': 'io',
    'nets.py': 'nets',
    'framework.py': '',
    'executor.py': '',
    'parallel_executor.py': '',
    'compiler.py': '',
    'backward.py': 'backward',
    'unique_name.py': 'unique_name',
    'dygraph/nn.py': 'dygraph',
    'dygraph/base.py': 'dygraph',
    'dygraph/checkpoint.py': 'dygraph',
    'dygraph/layers.py': 'dygraph',
    'dygraph/parallel.py': 'dygraph',
    'dygraph/learning_rate_scheduler.py': 'dygraph',
    'dygraph/jit.py': 'dygraph',
    'profiler.py': 'profiler',
    'data_feeder.py': '',
    'reader.py': '',
    'dataset.py': '',
    'param_attr.py': '',
}


def exported(path):
    try:
        tree = ast.parse(open(path).read())
    except (OSError, SyntaxError):
        return []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, 'id', '') == '__all__':
                    try:
                        return [e for e in ast.literal_eval(node.value)]
                    except Exception:
                        return []
    return []


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import paddle_tpu.fluid as fluid

    missing = {}
    total = have = 0
    for rel, attr in sorted(MODULES.items()):
        names = exported(os.path.join(REF_PY, rel))
        target = fluid
        if attr:
            for part in attr.split('.'):
                target = getattr(target, part, None)
                if target is None:
                    break
        for n in names:
            total += 1
            found = target is not None and hasattr(target, n) or \
                hasattr(fluid, n)
            if found:
                have += 1
            else:
                missing.setdefault(rel, []).append(n)
    print('reference public API symbols: %d; present: %d (%.1f%%)'
          % (total, have, 100.0 * have / max(total, 1)))
    for rel in sorted(missing):
        print('%s missing (%d): %s'
              % (rel, len(missing[rel]), ', '.join(missing[rel])))
    return 1 if missing else 0


if __name__ == '__main__':
    sys.exit(main())
