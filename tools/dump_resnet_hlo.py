"""Dump the optimized HLO of the ResNet-50 bench step and print the
definitions of the named fusions that dominate the profile
(tools/profile_resnet.py), so 'fusion.83' becomes actionable.

Usage: python tools/dump_resnet_hlo.py [fusion.83 fusion.81 ...]
Writes the full HLO to /tmp/resnet_step_hlo.txt.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.fluid.executor import _Segment, _make_segment_fn

    layout = 'NHWC'
    batch = 128
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 42
    with fluid.program_guard(main_p, startup):
        feeds, logits, loss, acc = models.resnet.build(data_format=layout)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Momentum(0.1, momentum=0.9),
            use_dynamic_loss_scaling=True)
        opt.minimize(loss)

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 224, 224, 3).astype('float32')
    y = rng.randint(0, 1000, (batch, 1)).astype('int32')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        plan = exe._build_plan(main_p, ('image', 'label'), ())
        segs = [it for it in plan if isinstance(it, _Segment)]
        seg = max(segs, key=lambda s: len(s.ops))
        fn = _make_segment_fn(seg)
        state = {n: fluid.core.as_array(scope.find_var(n))
                 for n in seg.state_names}
        data = {}
        for n in seg.input_names:
            data[n] = {'image': x, 'label': y}.get(
                n, fluid.core.as_array(scope.find_var(n)))
        compiled = jax.jit(fn, donate_argnums=(1,)).lower(
            0, state, data).compile()
    txt = compiled.as_text()
    with open('/tmp/resnet_step_hlo.txt', 'w') as f:
        f.write(txt)
    print('wrote %d lines to /tmp/resnet_step_hlo.txt'
          % len(txt.splitlines()))
    names = sys.argv[1:] or ['fusion.83', 'fusion.81', 'fusion.80',
                             'fusion.190', 'fusion.191', 'fusion.1718',
                             'fusion.189', 'convert_reduce_fusion.1',
                             'fusion.448', 'fusion.912', 'fusion.633']
    lines = txt.splitlines()
    for want in names:
        for i, ln in enumerate(lines):
            ls = ln.lstrip()
            if ls.startswith('%' + want + ' ') or \
                    ls.startswith(want + ' ') or \
                    (' = ' in ls and ls.split(' = ')[0].strip('%') == want):
                print('\n=== %s ===' % want)
                print(ln[:400])
                # print the fused computation it calls, if named
                import re
                m = re.search(r'calls=([%\w.\-]+)', ln)
                if m:
                    comp = m.group(1).lstrip('%')
                    for j, l2 in enumerate(lines):
                        if l2.startswith(comp + ' ') or \
                                l2.startswith('%' + comp + ' '):
                            for k in range(j, min(j + 25, len(lines))):
                                print(lines[k][:240])
                                if lines[k].rstrip().endswith('}'):
                                    break
                            break
                break


if __name__ == '__main__':
    main()
