"""Closed-loop autopilot gate: the telemetry-driven recalibration
plane must close the loop against a REAL executor and a REAL injected
fabric drift — and cost nothing when off (the fluid.autopilot analog
of check_timeseries.py's live-plane checks).

Three postures:

  1. live closed loop: phase 0 calibrates an honest comms model by
     fitting REAL dispatch points from a GradAllReduce program (the
     collective runner path), writes comms_model.json, then phase 1
     re-runs against that model with `collective.dispatch:delay`
     faultinjected into the measured dispatch wall.  The windowed
     honesty ratio (comms/plan_pred_over_measured) must collapse, the
     engaged autopilot must land a `refit: installed` decision on the
     step cadence (no thread), the repriced post-refit honesty median
     must re-converge into the band, and the pending refit must move
     NEITHER the plan digest nor the segments-lowered counters (zero
     retrace churn before the next explicit re-plan point).  The
     decision must be visible at /statusz (autopilot section) and the
     refit persisted to the sidecar; explicit adoption must move the
     digest exactly once;
  2. freeze + revert: with FLAGS_autopilot=0 a tick over a dishonest
     skew signal logs `acted=False` intents and leaves every knob
     bit-identical; one revert() restores the pre-engage bucket knob,
     clears the refit and removes the sidecar — digest back to the
     static plan;
  3. disabled-path cost: with the autopilot not engaged (the default),
     tools/check_hot_path.py's steady-state budgets must still hold —
     the step boundary pays one dict read for the whole plane.

Run from `make check` (CPU: JAX_PLATFORMS=cpu; the tool forces the
8-device host platform itself).
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _lowered():
    """Total segment lowerings across both runner paths — the
    zero-retrace-churn meter."""
    from paddle_tpu.fluid import monitor
    return ((monitor.counter_value('executor/segments_lowered') or 0.0)
            + (monitor.counter_value('parallel/segment_cache_miss')
               or 0.0))


def check_closed_loop(failures):
    """Posture 1: calibrate -> drift -> refit -> re-converge, live."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import (autopilot, comms, comms_plan,
                                  faultinject, layers, monitor, slo,
                                  timeseries)
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce

    tmp = tempfile.mkdtemp(prefix='check_autopilot_')
    model_path = os.path.join(tmp, 'comms_model.json')
    port = _free_port()
    band = 1.5
    fluid.set_flags({'FLAGS_comms_plan': True,
                     # split the grads across buckets so the fit sees
                     # >=2 distinct wire sizes (one fused bucket makes
                     # the intercept/slope split unidentifiable)
                     'FLAGS_comms_bucket_bytes': 32 << 10,
                     'FLAGS_comms_model_path': model_path,
                     'FLAGS_status_port': port,
                     'FLAGS_timeseries': True,
                     'FLAGS_autopilot': True,
                     # 0.0 falls back to the 2s default: use a small
                     # nonzero interval so ticks ride every step
                     'FLAGS_autopilot_interval_s': 0.05,
                     'FLAGS_autopilot_min_points': 4,
                     'FLAGS_autopilot_honesty_band': band})
    autopilot.reset()
    timeseries.reset()
    slo.reset()
    comms_plan.clear_refit()
    comms.clear_dispatch_points()

    def build():
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = 7
        with fluid.program_guard(main_p, startup):
            x = layers.data('x', shape=[64], dtype='float32')
            h = layers.fc(x, 1024, act='relu')
            h = layers.fc(h, 32, act='relu')
            loss = layers.reduce_mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
        # the weight grads (256KiB and 128KiB) land in DIFFERENT wire
        # size buckets, the biases fuse into a third: account_dispatch
        # aggregates points per (kind, size-bucket) series, and the
        # two-parameter fit needs >=2 distinct wire sizes
        GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                                  '127.0.0.1:0')
        return main_p, startup, loss

    feed = {'x': np.ones((8, 64), 'float32')}
    base = 'http://127.0.0.1:%d' % port

    # ---- phase 0: fit an honest model from real dispatch points
    main_p, startup, loss = build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(6):
            exe.run(main_p, feed=feed, fetch_list=[loss])
    pts = comms.dispatch_points('allreduce')
    sizes = {int(b) for b, _t in pts}
    if len(sizes) < 2:
        failures.append('phase 0 collected %d distinct allreduce wire '
                        'sizes (%r), need >=2 for a fit'
                        % (len(sizes), sorted(sizes)))
        return
    alpha, beta = comms.fit_linear(pts)
    with open(model_path, 'w') as f:
        json.dump({'collectives': {'allreduce': {
            'latency_s': alpha, 'inv_bw_s_per_byte': beta}}}, f)
    comms.clear_dispatch_points()

    # ---- phase 1: fresh program onto the model, then inject drift
    autopilot.engage()
    if not autopilot.engaged():
        failures.append('engage() did not latch')
        return
    main_p, startup, loss = build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(4):       # warm: trace onto the honest model
            exe.run(main_p, feed=feed, fetch_list=[loss])
        refits0 = monitor.counter_value('autopilot/refits') or 0.0
        for _ in range(4):       # honest steady state: no refit
            exe.run(main_p, feed=feed, fetch_list=[loss])
        if (monitor.counter_value('autopilot/refits') or 0.0) > refits0:
            failures.append('autopilot refit on an HONEST model '
                            '(honesty guard broken)')
        digest0 = comms_plan.digest()
        lowered0 = _lowered()

        # fabric drift: the delay lands INSIDE the measured dispatch
        # wall, so predictions go dishonest without any code change
        faultinject.configure('collective.dispatch:delay:0.05@1+')
        try:
            deadline = time.time() + 60
            while time.time() < deadline:
                exe.run(main_p, feed=feed, fetch_list=[loss])
                if (monitor.counter_value('autopilot/refits')
                        or 0.0) > refits0:
                    break
            refits1 = monitor.counter_value('autopilot/refits') or 0.0
            if refits1 <= refits0:
                failures.append(
                    'injected drift never triggered a refit '
                    '(decisions=%r)' % autopilot.decisions(last=5))
                return
            # post-refit: drive repriced honesty samples
            for _ in range(8):
                exe.run(main_p, feed=feed, fetch_list=[loss])
        finally:
            faultinject.reset()

        # decision log: an installed refit over the allreduce kind
        installed = [d for d in autopilot.decisions()
                     if d['kind'] == 'refit'
                     and d['choice'] == 'installed']
        if not installed:
            failures.append('no refit:installed decision in the log')
            return
        info = installed[-1].get('info') or {}
        if 'allreduce' not in (info.get('kinds') or {}):
            failures.append('refit decision did not refit allreduce: '
                            '%r' % info)
        if not (info.get('honesty') or 1.0) < 1.0 / band:
            failures.append('refit fired but recorded honesty %r was '
                            'not below the band' % info.get('honesty'))

        # honesty re-converged: windowed median SINCE the refit
        rep = autopilot.report()
        since = rep['last_refit_unix']
        doc = timeseries.window('comms/plan_pred_over_measured',
                                seconds=max(1e-3,
                                            time.time() - since))
        med = ((doc or {}).get('derived', {})
               .get('percentiles') or {}).get('p50')
        if med is None:
            failures.append('no post-refit honesty window (doc=%r)'
                            % (doc and doc.get('n')))
        elif not (1.0 / band <= med <= band):
            failures.append('post-refit honesty median %.4f did not '
                            're-converge into [%.3f, %.3f]'
                            % (med, 1.0 / band, band))

        # zero retrace churn: the pending refit moved neither the
        # plan digest nor any segment lowering counter
        if comms_plan.digest() != digest0:
            failures.append('pending refit moved the plan digest '
                            'before any re-plan point')
        if _lowered() != lowered0:
            failures.append('refit caused %d retraces post-warmup '
                            '(wanted 0)' % (_lowered() - lowered0))
        st = rep['refit']
        if not st['pending'] or st['adopted']:
            failures.append('refit slot wrong: %r (wanted pending, '
                            'not adopted)' % st)

        # sidecar persisted (atomically) next to the model file
        sidecar = model_path + '.refit.json'
        try:
            with open(sidecar) as f:
                side = json.load(f)
            if 'allreduce' not in side.get('collectives', {}):
                failures.append('sidecar misses allreduce: %r' % side)
        except Exception as e:
            failures.append('refit sidecar not persisted: %s' % e)

        # /statusz autopilot section over HTTP
        code, doc = _get_json(base + '/statusz')
        ap = doc.get('autopilot') if code == 200 else None
        if not ap or not ap.get('engaged'):
            failures.append('/statusz autopilot section missing or '
                            'not engaged (code=%d)' % code)
        elif not any(d.get('choice') == 'installed'
                     for d in ap.get('decisions', [])):
            failures.append('/statusz autopilot decisions miss the '
                            'installed refit')

        # explicit adoption is the one digest move (the executor does
        # this at warmup; here we drive it directly and stop stepping)
        comms_plan.adopt_refit()
        if comms_plan.digest() == digest0:
            failures.append('adoption did not move the plan digest')
        if not comms_plan.refit_state()['adopted']:
            failures.append('adopt_refit() did not latch')

    # ---- posture 2: freeze + revert, same live state
    bucket0 = fluid.get_flags(['FLAGS_comms_bucket_bytes'])[
        'FLAGS_comms_bucket_bytes']
    fluid.set_flags({'FLAGS_autopilot': False})
    frozen0 = monitor.counter_value('autopilot/frozen_intents') or 0.0
    monitor.set_gauge('comms/skew_ratio', 4.0)   # latency-dominated
    autopilot.tick(now=time.time() + 10)
    if fluid.get_flags(['FLAGS_comms_bucket_bytes'])[
            'FLAGS_comms_bucket_bytes'] != bucket0:
        failures.append('frozen tick changed FLAGS_comms_bucket_bytes')
    if (monitor.counter_value('autopilot/frozen_intents')
            or 0.0) <= frozen0:
        failures.append('frozen tick logged no intent')
    if any(d['acted'] and d['kind'] != 'engage'
           for d in autopilot.decisions()
           if d.get('frozen')):
        failures.append('a frozen decision claims acted=True')

    autopilot.revert()
    if comms_plan.refit_active():
        failures.append('revert left a refit installed')
    if os.path.exists(model_path + '.refit.json'):
        failures.append('revert left the refit sidecar on disk')
    cur = fluid.get_flags(['FLAGS_comms_bucket_bytes'])[
        'FLAGS_comms_bucket_bytes']
    static = autopilot.report()['static']['comms_bucket_bytes']
    if cur != static:
        failures.append('revert did not restore the bucket knob '
                        '(%r != static %r)' % (cur, static))
    autopilot.disengage()


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    sys.path.insert(0, ROOT)
    failures = []

    check_closed_loop(failures)

    # ---- 3: disabled-path hot-loop budgets ------------------------------
    env = dict(os.environ)
    env.pop('FLAGS_autopilot', None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools',
                                      'check_hot_path.py')],
        env=env, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        failures.append('check_hot_path budgets broke with the '
                        'autopilot hook on the sample cadence:\n%s'
                        % (r.stdout + r.stderr)[-800:])

    if failures:
        print('check_autopilot: FAIL')
        for f in failures:
            print('  - %s' % f)
        return 1
    print('check_autopilot: honest model held, injected fabric drift '
          'collapsed the honesty ratio, the autopilot refit on the '
          'step cadence and honesty re-converged with zero retrace '
          'churn (digest moved only at adoption), refit persisted + '
          'visible at /statusz, freeze left knobs bit-identical, one '
          'revert restored the static plan, hot-path budgets hold')
    return 0


if __name__ == '__main__':
    sys.exit(main())
