"""Fleet-plane gate: a live multi-replica fleet under a real skewed-
tenant soak must route, migrate and observe correctly (the
fluid.fleet analog of check_serving.py's single-replica gate).

Runs one in-process sequence:

  1. TWO ServingExecutor replicas behind one Fleet, three tenants
     (router-scored placement must spread them), full-ladder warmup;
  2. a two-thread SKEWED soak (~70% of traffic on one hot tenant,
     mixed row counts) through ``fleet.submit`` — sticky routing
     (placements unchanged), zero post-warmup retraces, every request
     served by its placed replica;
  3. a priced migration of the hot tenant mid-soak-shape traffic:
     bitwise-equal results on the target, zero retraces after the
     pre-warm, the decision log carries the price;
  4. router decisions observable over HTTP: ``/statusz`` must carry
     the ``fleet`` section (replicas, placements, decision trail) and
     the merged ``/metrics`` must pass the fluid.health prom_lint;
  5. disabled-path budget: with no live fleet, ``fleet.maybe_tick``
     must cost one weak-set read (10k ticks under a wall budget) and
     leave no ``fleet/*`` counters behind.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

SOAK_REQUESTS_PER_THREAD = 24
DISABLED_TICKS = 10000
DISABLED_BUDGET_S = 0.5


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode('utf-8')
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode('utf-8')


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import (fleet, health, layers, memviz,
                                  monitor, serving)

    failures = []

    def build(width, seed):
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = seed
        with fluid.program_guard(main_p, startup):
            x = layers.data('x', shape=[16], dtype='float32')
            h = layers.fc(x, width, act='relu')
            y = layers.fc(h, 10, act='softmax')
        return main_p, startup, y

    # -- 5 (first: needs a clean registry). disabled-path budget ------
    t0 = time.perf_counter()
    for _ in range(DISABLED_TICKS):
        fleet.maybe_tick()
    wall = time.perf_counter() - t0
    if wall > DISABLED_BUDGET_S:
        failures.append('no-fleet maybe_tick cost %.3fs for %d calls '
                        '(budget %.1fs): the disabled plane must be '
                        'one weak-set read'
                        % (wall, DISABLED_TICKS, DISABLED_BUDGET_S))
    if monitor.counter_value('fleet/ticks'):
        failures.append('no-fleet maybe_tick left fleet/ticks = %g'
                        % monitor.counter_value('fleet/ticks'))

    # -- 1. two replicas, three tenants, scored spread ----------------
    exe = fluid.Executor(fluid.XLAPlace(0))
    fl = fleet.Fleet()
    for i in range(2):
        fl.add_replica('r%d' % i,
                       serving.ServingExecutor(max_batch=8,
                                               executor=exe))
    tenants = {}
    for name, (w, s, cls) in (('hot', (32, 11, 'interactive')),
                              ('warm', (48, 12, 'interactive')),
                              ('cold', (24, 13, 'batch'))):
        mp, sp, y = build(w, s)
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(sp)
        tenants[name] = (mp, sc, y)
        fl.register_tenant(name, mp, ['x'], [y], scope=sc,
                           slo_class=cls)
    placed = fl.placement()
    if set(placed.values()) != {'r0', 'r1'}:
        failures.append('router packed every tenant onto %r (want a '
                        'spread across both replicas)'
                        % sorted(set(placed.values())))
    fl.warmup(wait=True)
    memviz.live_census()      # the pricing input for leg 3

    # -- 2. skewed two-thread soak: sticky, zero-retrace --------------
    lowered0 = monitor.counter_value('executor/segments_lowered')
    results = {}
    errors = []

    def feeder(tid):
        rng = np.random.RandomState(100 + tid)
        for i in range(SOAK_REQUESTS_PER_THREAD):
            # ~70% of traffic on the hot tenant — the skew the router
            # and balance loop exist for
            name = ('hot', 'hot', 'hot', 'warm', 'hot',
                    'cold', 'hot', 'hot', 'warm', 'hot')[i % 10]
            rows = (1, 3, 2, 7, 4)[i % 5]
            xv = rng.randn(rows, 16).astype('float32')
            try:
                out, = fl.submit(name, {'x': xv}).result(120)
                results[(tid, i)] = (name, xv, np.asarray(out))
            except Exception as e:  # noqa: BLE001
                errors.append('feeder %d req %d: %s' % (tid, i, e))

    threads = [threading.Thread(target=feeder, args=(tid,))
               for tid in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    if errors:
        failures.append('soak errors: %s' % '; '.join(errors[:3]))
    if len(results) != 2 * SOAK_REQUESTS_PER_THREAD:
        failures.append('soak served %d/%d requests'
                        % (len(results),
                           2 * SOAK_REQUESTS_PER_THREAD))
    lowered_soak = monitor.counter_value(
        'executor/segments_lowered') - lowered0
    if lowered_soak:
        failures.append('fleet soak retraced: %g segments lowered '
                        'after warmup' % lowered_soak)
    if fl.placement() != placed:
        failures.append('soak moved placements %r -> %r (stickiness)'
                        % (placed, fl.placement()))
    routed = monitor.counter_value('fleet/routed_requests')
    if routed != 2 * SOAK_REQUESTS_PER_THREAD:
        failures.append('fleet/routed_requests %g != %d'
                        % (routed, 2 * SOAK_REQUESTS_PER_THREAD))

    # -- 3. priced migration of the hot tenant ------------------------
    rng = np.random.RandomState(7)
    xv = rng.randn(3, 16).astype('float32')
    before = np.asarray(fl.submit('hot', {'x': xv}).result(120)[0])
    src = fl.placement('hot')
    tgt = fl.migrate('hot', why='check_fleet')
    if tgt is None or tgt == src:
        failures.append('migration returned %r (from %r)' % (tgt, src))
    lowered_mig = monitor.counter_value('executor/segments_lowered')
    after = np.asarray(fl.submit('hot', {'x': xv}).result(120)[0])
    if not np.array_equal(before, after):
        failures.append('post-migration result differs bitwise')
    if monitor.counter_value('executor/segments_lowered') != \
            lowered_mig:
        failures.append('post-migration submit retraced')
    migs = [d for d in fleet.decisions() if d['kind'] == 'migrate'
            and d['acted']]
    if not migs:
        failures.append('no acted migrate decision in the log')
    else:
        priced = migs[-1]['info'].get('priced') or {}
        if 'residency_bytes' not in priced or \
                'measured_warmup_s' not in priced:
            failures.append('migrate decision not priced: %r' % priced)

    # -- 4. decisions over HTTP + lint-clean /metrics -----------------
    srv = health.serve(port=0)
    try:
        code, text = _get(srv.url + '/statusz')
        sec = (json.loads(text) or {}).get('fleet') if code == 200 \
            else None
        if code != 200 or not sec:
            failures.append('/statusz fleet section missing '
                            '(HTTP %s)' % code)
        else:
            body = (sec.get('fleets') or [{}])[0]
            if set(body.get('replicas', {})) != {'r0', 'r1'}:
                failures.append('/statusz fleet replicas %r'
                                % sorted(body.get('replicas', {})))
            if not sec.get('decisions'):
                failures.append('/statusz fleet carries no decisions')
            kinds = {d['kind'] for d in sec.get('decisions', ())}
            if 'place' not in kinds or 'migrate' not in kinds:
                failures.append('/statusz fleet decision kinds %r '
                                'missing place/migrate'
                                % sorted(kinds))
        code, text = _get(srv.url + '/metrics')
        problems = health.prom_lint(text)
        if code != 200:
            failures.append('/metrics HTTP %s' % code)
        if problems:
            failures.append('/metrics lint: %s'
                            % '; '.join(problems[:5]))
    finally:
        srv.stop()

    for s in fl.replicas().values():
        s.close()
    fl.close()
    print('fleet soak: %d requests over 2 replicas, placements %s, '
          '%g retraces, %d decisions'
          % (len(results), placed, lowered_soak,
             len(fleet.decisions())))
    if failures:
        for f in failures:
            print('FAIL  ' + f)
        return 1
    print('fleet plane: OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
