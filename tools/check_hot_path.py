"""Hot-path regression gate: the steady-state step fast path must stay
fast (the runtime analog of check_stat_coverage.py's static audit).

Runs a small TWO-SEGMENT program (device segment -> py_func host op ->
device segment) for a handful of steps with device-resident feeds and
async fetches, then checks the per-step monitor counters of the
POST-WARMUP window against budgets:

  - executor/scope_lookups      == 0   (every bind hits the cached
                                        owner tables; a regression that
                                        re-walks the scope per step
                                        shows up here first)
  - executor/fastpath_hits      == steps * segments
  - executor/h2d_bytes_async    == 0   (feeds are device-resident;
                                        a defensive re-copy of state or
                                        feed data would reappear here)
  - executor/fetch_blocked_seconds count == 0 for the unresolved-async
                                        window (dispatch never blocks
                                        on D2H)
  - executor/bind_seconds mean  <  BIND_BUDGET_S (generous wall budget
                                        for the flat bind loop itself)

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import os
import sys

BIND_BUDGET_S = float(os.environ.get('PADDLE_TPU_BIND_BUDGET_S', 0.005))
WARMUP = 3
STEPS = 8


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import numpy as np
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[16], dtype='float32')
        h = layers.fc(x, 16, act='relu')
        mid = main_p.current_block().create_var(
            name='hot_mid', shape=[-1, 16], dtype='float32')
        layers.py_func(lambda a: a, h, mid)   # host op: cuts 2 segments
        h2 = layers.fc(mid, 8, act='relu')
        loss = layers.reduce_mean(h2)
        fluid.optimizer.SGD(0.05).minimize(loss)

    xs = jax.device_put(
        np.random.RandomState(0).randn(8, 16).astype('float32'))
    failures = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        # warm up the SAME call signature as the timed window (fetch
        # set keys the plan; a different signature would compile and
        # resolve binders inside the window)
        for _ in range(WARMUP):
            w_, = exe.run(main_p, feed={'x': xs}, fetch_list=[loss],
                          return_numpy='async')
            w_.as_numpy()
        f0 = monitor.flat()
        handles = []
        for _ in range(STEPS):
            h_, = exe.run(main_p, feed={'x': xs}, fetch_list=[loss],
                          return_numpy='async')
            handles.append(h_)
        f1 = monitor.flat()
        # resolution correctness stays part of the gate: every handle
        # must produce a finite loss once resolved
        vals = [float(np.asarray(h_).ravel()[0]) for h_ in handles]
        if not np.isfinite(vals).all():
            failures.append('async fetches resolved non-finite: %r'
                            % (vals,))

    def delta(key):
        return f1.get(key, 0.0) - f0.get(key, 0.0)

    n_segments = 2
    checks = [
        ('executor/scope_lookups per step', delta('executor/scope_lookups'),
         0.0),
        ('executor/h2d_bytes_async per step',
         delta('executor/h2d_bytes_async'), 0.0),
        ('executor/fetch_blocked_seconds count (pre-resolve)',
         delta('executor/fetch_blocked_seconds/count'), 0.0),
    ]
    for name, got, budget in checks:
        if got > budget:
            failures.append('%s regressed: %g (budget %g)'
                            % (name, got, budget))
    hits = delta('executor/fastpath_hits')
    want_hits = STEPS * n_segments
    if hits != want_hits:
        failures.append('executor/fastpath_hits: %g, expected %d '
                        '(every steady-state bind must hit the cached '
                        'tables)' % (hits, want_hits))
    bind_n = delta('executor/bind_seconds/count')
    bind_s = delta('executor/bind_seconds/sum')
    if bind_n and bind_s / bind_n > BIND_BUDGET_S:
        failures.append('executor/bind_seconds mean %.6fs exceeds '
                        'budget %.6fs' % (bind_s / bind_n,
                                          BIND_BUDGET_S))
    print('hot path: %d steps x %d segments, %g fastpath hits, '
          '%.1fus mean bind, %g B async H2D'
          % (STEPS, n_segments, hits,
             1e6 * bind_s / max(bind_n, 1),
             delta('executor/h2d_bytes_async')))
    if failures:
        for f in failures:
            print('HOT-PATH REGRESSION  ' + f)
        return 1
    print('hot path: within budget')
    return 0


if __name__ == '__main__':
    sys.exit(main())
