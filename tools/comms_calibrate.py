"""Collective cost-model calibrator: sweep collective payload sizes
through the REAL collective runner and fit the measured times into
``comms_model.json`` — per-collective, per-axis latency (alpha) +
inverse bandwidth (beta), the T(b) = alpha + beta*b model the
topology-aware collective synthesis (ROADMAP item 3, arXiv:2110.10548)
and the EQuARX quantized-allreduce gate (arXiv:2506.17615) consume.

Each swept point builds a one-collective program (a fed buffer and a
single c_allreduce_sum / c_allgather / c_reducescatter / c_broadcast
op), runs it through Executor.run's collective path (shard_map over
the 'dp' mesh axis, exactly the path a GradAllReduce-transpiled
trainer takes), and records the median steady-step wall time — so the
model prices what training steps actually pay, host overheads
included (alpha absorbs them).  The fluid.comms telemetry that
accumulates during the sweep (comms/bytes_on_wire, achieved-bandwidth
histograms) is written into the artifact alongside the fit, and every
point carries its predicted/measured ratio: tools/check_comms.py
gates max_ratio <= 2.0.

Usage (8-device CPU mesh, the CI posture):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/comms_calibrate.py --out comms_model.json
  --sizes_kib 16,64,256,1024   per-participant payloads to sweep
  --steps 8                    timed steps per point (median)
  --collectives allreduce,allgather,reducescatter,broadcast,allreduce_quant
  --quick                      small sweep for CI gates

The ``allreduce_quant`` kind sweeps the planner's quantized arm (the
c_allreduce_sum lowering with plan_arm='quant': int8 reduce-scatter +
per-block fp32 scales + int8 allgather), priced at its actual
quantized wire bytes (comms_plan.quant_wire_bytes) — so
comms_model.json carries a real measured entry for the quant-vs-dense
decision, not a scaled guess.
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _percentile(vals, q):
    s = sorted(vals)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def build_program(fluid, layers, kind, elems, ndev):
    """One-collective program: feed 'x', run the collective, fetch the
    result.  Shapes are chosen so each mesh shard holds `elems`
    float32s (reducescatter needs its scatter dim divisible by the
    axis size)."""
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_p, startup):
        if kind == 'reducescatter':
            row = max(1, elems // ndev)
            x = layers.data('x', shape=[row], dtype='float32')
        else:
            x = layers.data('x', shape=[elems], dtype='float32')
    block = main_p.global_block()
    op_type = {'allreduce': 'c_allreduce_sum',
               'allreduce_quant': 'c_allreduce_sum',
               'allgather': 'c_allgather',
               'reducescatter': 'c_reducescatter',
               'broadcast': 'c_broadcast'}[kind]
    if kind in ('allreduce', 'allreduce_quant', 'broadcast'):
        fetch = 'x'
        attrs = {'ring_id': 0}
        if kind == 'allreduce_quant':
            # force the quantized arm (int8 reduce-scatter + scales)
            # regardless of the FLAGS_comms_quantize gate, so the model
            # can price it against dense
            attrs['plan_arm'] = 'quant'
        block.append_op(op_type, inputs={'X': 'x'},
                        outputs={'Out': 'x'},
                        attrs=attrs, infer_shape=False)
    else:
        block.create_var(name='y', shape=x.shape, dtype='float32')
        fetch = 'y'
        block.append_op(op_type, inputs={'X': 'x'},
                        outputs={'Out': 'y'},
                        attrs={'ring_id': 0}, infer_shape=False)
    main_p._collective_dp = True
    if kind == 'reducescatter':
        # per-shard [ndev, elems//ndev]: the scatter dim stays
        # divisible by the axis size
        feed_shape = (ndev * ndev, max(1, elems // ndev))
    else:
        feed_shape = (ndev, elems)
    return main_p, fetch, feed_shape


def sweep(kinds, sizes_kib, steps, warmup):
    import numpy as np
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import comms, comms_plan, layers, monitor

    ndev = len(jax.devices())
    exe = fluid.Executor(fluid.XLAPlace(0))
    results = {}
    for kind in kinds:
        points = []
        for kib in sizes_kib:
            elems = max(ndev, (kib * 1024) // 4)
            main_p, fetch, feed_shape = build_program(
                fluid, layers, kind, elems, ndev)
            rng = np.random.RandomState(1)
            feed = {'x': rng.rand(*feed_shape).astype('float32')}
            with fluid.scope_guard(fluid.Scope()):
                for _ in range(max(1, warmup)):
                    exe.run(main_p, feed=feed, fetch_list=[fetch])
                walls = []
                for _ in range(steps):
                    t0 = time.perf_counter()
                    exe.run(main_p, feed=feed, fetch_list=[fetch])
                    walls.append(time.perf_counter() - t0)
            payload = float(elems * 4)
            if kind == 'allreduce_quant':
                wire = comms_plan.quant_wire_bytes(payload, 4, ndev)
            else:
                wire = comms.wire_bytes(kind, payload, ndev)
            # fit target is the MIN wall: the uncontended cost of the
            # collective, the estimate a planner should price with —
            # OS jitter only ever inflates a sample (p50/p90 ride
            # along for the contended view)
            best = min(walls)
            points.append({
                'payload_bytes': payload,
                'wire_bytes': wire,
                'measured_s': best,
                'measured_p50_s': _percentile(walls, 0.5),
                'measured_p90_s': _percentile(walls, 0.9),
                'achieved_gbps': (wire / best / 1e9) if best > 0
                                 else 0.0,
            })
            print('  %-13s %8d KiB/shard  min %8.3f ms  p50 %8.3f ms'
                  '  %7.4f GB/s'
                  % (kind, payload // 1024, best * 1e3,
                     points[-1]['measured_p50_s'] * 1e3,
                     points[-1]['achieved_gbps']), flush=True)
        alpha, beta = comms.fit_linear(
            [(p['wire_bytes'], p['measured_s']) for p in points])
        for p in points:
            pred = alpha + beta * p['wire_bytes']
            p['predicted_s'] = pred
            m = p['measured_s']
            p['ratio'] = max(pred / m, m / pred) if m > 0 and pred > 0 \
                else float('inf')
        results[kind] = {
            'axis': 'dp',
            'participants': ndev,
            'latency_s': alpha,
            'inv_bw_s_per_byte': beta,
            'bw_gbps': (1.0 / beta / 1e9) if beta > 0 else 0.0,
            'max_ratio': max(p['ratio'] for p in points),
            'points': points,
        }
    counters = {k: v for k, v in monitor.flat().items()
                if k.startswith('comms/')}
    return ndev, results, counters


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default='comms_model.json')
    ap.add_argument('--sizes_kib', default=None,
                    help='comma list of per-participant payload KiB')
    ap.add_argument('--steps', type=int, default=8)
    ap.add_argument('--warmup', type=int, default=2)
    ap.add_argument('--collectives',
                    default='allreduce,allgather,reducescatter,'
                            'broadcast,allreduce_quant')
    ap.add_argument('--quick', action='store_true',
                    help='small sweep (CI gate posture)')
    args = ap.parse_args(argv)
    sys.path.insert(0, ROOT)

    if args.sizes_kib:
        sizes = [int(s) for s in args.sizes_kib.split(',') if s]
    elif args.quick:
        sizes = [16, 256, 1024]
    else:
        sizes = [16, 64, 256, 1024, 4096]
    kinds = [k.strip() for k in args.collectives.split(',') if k.strip()]
    steps = max(3, args.steps // 2) if args.quick else args.steps

    import jax
    ndev, results, counters = sweep(kinds, sizes, steps, args.warmup)
    model = {
        'version': 1,
        'backend': jax.default_backend(),
        'devices': ndev,
        'mesh_axes': {'dp': ndev},
        'collectives': results,
        'comms_counters': counters,
        'meta': {
            'created_unix': time.time(),
            'steps_per_point': steps,
            'sizes_kib': sizes,
            'jax': jax.__version__,
            'cmd': 'python tools/comms_calibrate.py',
        },
    }
    d = os.path.dirname(os.path.abspath(args.out))
    os.makedirs(d, exist_ok=True)
    with open(args.out, 'w') as f:
        json.dump(model, f, indent=1, sort_keys=True)
    worst = max(r['max_ratio'] for r in results.values())
    print('comms model written to %s (%d collectives x %d sizes, '
          'worst predicted/measured ratio %.2fx)'
          % (args.out, len(results), len(sizes), worst))
    return 0


if __name__ == '__main__':
    sys.exit(main())
