"""Chaos soak gate: a REAL multi-process training job driven through
scripted ``faultinject`` clauses must reach its target step with ZERO
human intervention, bounded lost work, a bitwise-reproducible
post-recovery trajectory, and EVERY injected fault matched to a named
supervisor decision in /statusz.

Injected fault kinds (>= 4 distinct, all in one soak):

  worker kill       peer worker armed with 'executor.step:die@N' — a
                    real kill -9 mid-step; the rank-0 supervisor must
                    confirm the death through the aggregator's
                    consecutive-miss signal and degrade to the
                    survivors ('death' -> 'recovery' decisions)
  torn shard write  'elastic.shard_write:torn@K' tears one shard of a
                    periodic checkpoint; the supervisor's post-save
                    digest verification must catch it and resave
                    ('checkpoint_torn' decision) so lost work stays
                    bounded by ONE cadence
  RPC stall/fault   'rpc.call:fail@N' injects a transport failure
                    into the live PS heartbeat; the rpc_ps
                    bounded-backoff machinery absorbs it and the
                    supervisor logs the tolerated degradation
                    ('rpc_backoff' decision)
  heartbeat flap    a peer's status endpoint goes unreachable for
                    less than FLAGS_heartbeat_misses scrapes and
                    recovers — a real network-level drop-and-recover;
                    must be tolerated ('heartbeat_flap'), NEVER
                    resharded
  collective stall  'executor.dispatch:stall:S@N' parks a segment
                    dispatch past FLAGS_step_timeout_s; the hung-step
                    watchdog converts it into a named timeout + flight
                    dump and the supervisor recovers from last-good
                    ('hung_step' -> 'recovery')

Topology note: same cluster-in-a-box posture as check_elastic /
check_supervisor (cross-process jax collectives are unavailable on the
CPU backend) — every kill, scrape, RPC frame and restart crosses a
real OS process boundary, which is what the controller gates.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).  ``bench.py --chaos``
drives this same soak and records the stats line (CHAOS_STATS) into
BENCH_chaos.json.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TARGET_STEP = 24
CADENCE = 4
HEARTBEAT_S = 0.25
MISSES = 3
STEP_TIMEOUT_S = 0.7
REJOIN_WAIT_S = 8.0
STALL_HIT = 12          # executor.dispatch hit of the injected stall
RPC_FAIL_HIT = 6        # rpc.call hit of the injected transport fault
FLAP_START_S = 6.0      # flapper outage window, relative to its start
FLAP_LEN_S = 0.4        # < MISSES * HEARTBEAT_S: a flap, not a death


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def build_model():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import unique_name
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 23
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            y = fluid.layers.data('y', shape=[1], dtype='float32')
            h = fluid.layers.fc(x, 16, act='relu')
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.reduce_mean(fluid.layers.square(
                fluid.layers.elementwise_sub(pred, y)))
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def batch_for(step, n=8):
    import numpy as np
    rng = np.random.RandomState(4000 + step)
    x = rng.randn(n, 8).astype('float32')
    return x, (x.sum(1, keepdims=True) * 0.5).astype('float32')


def _hex(v):
    import numpy as np
    return np.float32(np.asarray(v).ravel()[0]).tobytes().hex()


# -------------------------------------------------------------- workers
def victim_main():
    """Peer worker 1: dies by a REAL kill -9 mid-step (faultinject
    'executor.step:die' in its env)."""
    import paddle_tpu.fluid as fluid
    main, startup, loss = build_model()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        time.sleep(1.0)      # visibly UP for the aggregator first
        for s in range(1000):
            x, y = batch_for(s)
            exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
            time.sleep(0.1)
    print('VICTIM_SURVIVED')


def flapper_main(port):
    """Peer worker 2: a status endpoint that goes dark for
    FLAP_LEN_S (< the miss tolerance) and recovers — the
    heartbeat-drop-and-recover fault, at the real network level."""
    import http.server
    t0 = time.time()
    body = json.dumps({
        'rank': '2',
        'state': {'counters': {}, 'gauges': {}, 'hists': {}},
        'status': {'ready': True, 'steps': 1},
        'step_rollup': None}).encode()

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            dt = time.time() - t0
            if FLAP_START_S <= dt < FLAP_START_S + FLAP_LEN_S:
                time.sleep(3.0)    # outlives the scrape timeout
            try:
                self.send_response(200)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except Exception:
                pass

    httpd = http.server.ThreadingHTTPServer(('127.0.0.1', int(port)),
                                            H)
    httpd.daemon_threads = True
    httpd.serve_forever()


def soak_main(store):
    """Rank 0: the supervised trainer every fault lands on."""
    import urllib.request
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import faultinject, monitor, supervisor
    from paddle_tpu.fluid.io import _persistable_vars
    main, startup, loss = build_model()
    nshards = len(_persistable_vars(main))
    # deterministic chaos plan, computed against THIS model: tear the
    # first shard of checkpoint #2, fail one heartbeat RPC, stall one
    # steady-state dispatch past the watchdog deadline
    clauses = ['elastic.shard_write:torn@%d' % (nshards + 1),
               'executor.dispatch:stall:2@%d' % STALL_HIT]
    rpc_ok = False
    ps = hb = None
    try:
        from paddle_tpu.distributed.rpc_ps import PsServer
        ps = PsServer()
        rpc_ok = True
        clauses.append('rpc.call:fail@%d' % RPC_FAIL_HIT)
    except Exception:
        ps = None      # native runtime unavailable: 4 kinds remain
    faultinject.configure(';'.join(clauses))

    losses = {}
    recoveries = []
    timeouts = 0
    required = {'death', 'recovery', 'checkpoint_torn',
                'heartbeat_flap', 'hung_step'}
    if rpc_ok:
        required.add('rpc_backoff')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        if rpc_ok:
            from paddle_tpu.distributed.rpc_ps import TrainerHeartbeat
            hb = TrainerHeartbeat(ps.endpoint, 0, timeout=30.0,
                                  interval=0.1)
        x0, y0 = batch_for(0)
        supervisor.attach(store, program=main, executor=exe,
                          checkpoint_steps=CADENCE,
                          rejoin_wait_s=REJOIN_WAIT_S,
                          feed_shapes={'x': x0, 'y': y0},
                          fetch_list=[loss])
        deadline = time.time() + 120
        target = TARGET_STEP
        try:
            while time.time() < deadline:
                s = int(exe._step)
                seen = {d['kind'] for d in supervisor.decisions()}
                if s >= target and required <= seen:
                    break
                x, y = batch_for(s)
                try:
                    l, = exe.run(main, feed={'x': x, 'y': y},
                                 fetch_list=[loss])
                    losses[int(exe._step)] = _hex(l)
                except supervisor.Recovered as e:
                    recoveries.append({
                        'generation': e.generation, 'step': e.step,
                        'lost_steps': e.lost_steps,
                        'wall': time.time()})
                    target = max(TARGET_STEP, e.step + 6)
                    continue
                except supervisor.StepTimeoutError:
                    timeouts += 1
                    continue   # next run() executes the recovery
                time.sleep(0.1)
            decs = supervisor.decisions()
            # the /statusz proof: every fault's decision is scrapeable
            port = int(fluid.get_flags('FLAGS_status_port')
                       ['FLAGS_status_port'])
            with urllib.request.urlopen(
                    'http://127.0.0.1:%d/statusz' % port,
                    timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            section = doc.get('supervisor') or {}
            statusz_kinds = sorted({d['kind'] for d in
                                    section.get('decisions', [])})
        finally:
            sup = supervisor.current()
            t = sup._save_thread if sup else None
            supervisor.detach()
            if t is not None:
                t.join(timeout=10)
            if hb is not None:
                hb.stop()
            if ps is not None:
                ps.stop()
    out = {
        'losses': losses,
        'recoveries': recoveries,
        'timeouts': timeouts,
        'final_step': int(exe._step),
        'rpc_ok': rpc_ok,
        'decisions': [{k: d.get(k) for k in
                       ('kind', 'choice', 'acted', 'fault',
                        'wall_unix', 'info')} for d in decs],
        'statusz_kinds': statusz_kinds,
        'faultinject': faultinject.report(),
        'counters': {k: monitor.counter_value(k) for k in (
            'supervisor/checkpoints_taken', 'supervisor/checkpoint_torn',
            'supervisor/recoveries', 'supervisor/deaths_confirmed',
            'supervisor/lost_steps', 'supervisor/hung_steps',
            'executor/step_timeouts', 'elastic/heartbeat_flaps',
            'elastic/refused_generations', 'rpc/retries')},
    }
    print('CHECK_JSON ' + json.dumps(out))


def verify_main(store, generation, target):
    """Bitwise-reproducibility reference: a fresh process resumes the
    LAST recovery's generation and replays to the same step."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import elastic
    main, startup, loss = build_model()
    losses = {}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        elastic.load_checkpoint(store, main, executor=exe,
                                generation=int(generation))
        while exe._step < int(target):
            s = int(exe._step)
            x, y = batch_for(s)
            l, = exe.run(main, feed={'x': x, 'y': y},
                         fetch_list=[loss])
            losses[int(exe._step)] = _hex(l)
    print('CHECK_JSON ' + json.dumps({'losses': losses}))


# -------------------------------------------------------------- driver
def _spawn(mode, args, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), '--child', mode]
        + [str(a) for a in args],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _child_json(stdout, tag=''):
    for line in stdout.splitlines():
        if line.startswith('CHECK_JSON '):
            return json.loads(line[len('CHECK_JSON '):])
    raise RuntimeError('%s produced no CHECK_JSON\n%s'
                       % (tag, stdout[-2000:]))


def run_soak():
    """The whole soak; returns (failures, stats) so bench.py --chaos
    can record the stats without re-implementing the harness."""
    work = tempfile.mkdtemp(prefix='pt_chaos_')
    store = os.path.join(work, 'store')
    p0, p1, p2 = _free_port(), _free_port(), _free_port()
    spec = ('0=127.0.0.1:%d,1=127.0.0.1:%d,2=127.0.0.1:%d'
            % (p0, p1, p2))
    common = {
        'PADDLE_TPU_STATUS_WORKERS': spec,
        'FLAGS_health_heartbeat_seconds': str(HEARTBEAT_S),
        'FLAGS_heartbeat_misses': str(MISSES),
        'FLAGS_trace': '1',
        'FLAGS_elastic_keep_generations': '64',
    }
    failures = []
    stats = {}
    procs = []
    try:
        flapper = _spawn('flapper', [p2])
        procs.append(flapper)
        victim = _spawn('victim', [], dict(
            common, PADDLE_TRAINER_ID='1', FLAGS_status_port=str(p1),
            FLAGS_faultinject='executor.step:die@6'))
        procs.append(victim)
        t_start = time.time()
        soak = _spawn('soak', [store], dict(
            common, PADDLE_TRAINER_ID='0', FLAGS_status_port=str(p0),
            FLAGS_step_timeout_s=str(STEP_TIMEOUT_S)))
        procs.append(soak)
        s_out, s_err = soak.communicate(timeout=300)
        soak_wall = time.time() - t_start
        v_rc = victim.wait(timeout=60)
        if v_rc != 9:
            failures.append('victim exited %r, wanted kill -9 code 9'
                            % v_rc)
        if soak.returncode != 0:
            failures.append('soak child exited %d (manual '
                            'intervention would have been needed)\n%s'
                            % (soak.returncode, s_err[-3000:]))
            return failures, stats
        res = _child_json(s_out, tag='soak')
        kinds = sorted({d['kind'] for d in res['decisions']})
        fired = res['faultinject'].get('fired', {})
        print('soak: final step %d, %d recoveries, %d checkpoints '
              '(%d torn->resaved), decisions %s, fired %s'
              % (res['final_step'], len(res['recoveries']),
                 res['counters']['supervisor/checkpoints_taken'],
                 res['counters']['supervisor/checkpoint_torn'],
                 kinds, fired))

        # 1. zero-intervention completion
        if res['final_step'] < TARGET_STEP:
            failures.append('soak stopped at step %d < target %d'
                            % (res['final_step'], TARGET_STEP))

        # 2. every injected fault matched to a NAMED decision, both in
        #    the child's log and in the scraped /statusz section
        matches = [('worker kill (kill -9 rc=9)', True, 'death'),
                   ('worker kill recovery', True, 'recovery'),
                   ('torn shard write',
                    fired.get('elastic.shard_write', 0) >= 1,
                    'checkpoint_torn'),
                   ('heartbeat flap', res['counters'][
                       'elastic/heartbeat_flaps'] >= 1,
                    'heartbeat_flap'),
                   ('collective stall',
                    fired.get('executor.dispatch', 0) >= 1,
                    'hung_step'),
                   ('rpc fault', res['rpc_ok'] and
                    fired.get('rpc.call', 0) >= 1, 'rpc_backoff')]
        injected_kinds = 0
        for label, injected, kind in matches:
            if not injected:
                if kind in ('checkpoint_torn', 'hung_step',
                            'heartbeat_flap'):
                    failures.append('%s was never injected' % label)
                continue
            injected_kinds += 1
            if kind not in kinds:
                failures.append('injected fault %r has no %r '
                                'decision in the log' % (label, kind))
            if kind not in res['statusz_kinds']:
                failures.append('injected fault %r has no %r '
                                'decision in /statusz' % (label, kind))
        distinct = len({k for _l, inj, k in matches
                        if inj and k not in ('recovery',)})
        if distinct < 4:
            failures.append('only %d distinct fault kinds injected, '
                            'need >= 4' % distinct)

        # 3. bounded lost work: <= one checkpoint cadence per recovery
        for r in res['recoveries']:
            if r['lost_steps'] > CADENCE:
                failures.append('recovery from gen %s lost %d steps '
                                '> cadence %d'
                                % (r['generation'], r['lost_steps'],
                                   CADENCE))

        # 4. bitwise-reproducible post-recovery trajectory
        compared = 0
        if res['recoveries']:
            last = res['recoveries'][-1]
            target = max(int(s) for s in res['losses'])
            verify = _spawn('verify',
                            [store, last['generation'], target])
            vout, verr = verify.communicate(timeout=240)
            if verify.returncode != 0:
                failures.append('verifier exited %d\n%s'
                                % (verify.returncode, verr[-2000:]))
            else:
                ref = _child_json(vout, tag='verify')['losses']
                for s, hx in ref.items():
                    if int(s) <= last['step']:
                        continue
                    got = res['losses'].get(s)
                    if got is None:
                        continue
                    compared += 1
                    if got != hx:
                        failures.append(
                            'post-recovery step %s not bitwise-'
                            'reproducible: %s vs %s' % (s, got, hx))
                if compared < 3:
                    failures.append('only %d post-recovery steps '
                                    'compared bitwise' % compared)
        else:
            failures.append('no recovery ever happened')

        stats = {
            'soak_wall_s': round(soak_wall, 2),
            'final_step': res['final_step'],
            'target_step': TARGET_STEP,
            'checkpoint_cadence_steps': CADENCE,
            'fault_kinds_injected': distinct,
            'recoveries': len(res['recoveries']),
            'lost_steps': [r['lost_steps'] for r in res['recoveries']],
            'step_timeouts': res['counters']['executor/step_timeouts'],
            'checkpoints_taken': res['counters'][
                'supervisor/checkpoints_taken'],
            'checkpoints_torn_resaved': res['counters'][
                'supervisor/checkpoint_torn'],
            'heartbeat_flaps_tolerated': res['counters'][
                'elastic/heartbeat_flaps'],
            'rpc_retries': res['counters']['rpc/retries'],
            'decision_kinds': kinds,
            'bitwise_steps_verified': compared,
            'rpc_ok': res['rpc_ok'],
        }
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGKILL)
                except OSError:
                    pass
        shutil.rmtree(work, ignore_errors=True)
    return failures, stats


def main():
    if '--child' in sys.argv:
        i = sys.argv.index('--child')
        sys.path.insert(0, REPO)
        mode = sys.argv[i + 1]
        if mode == 'victim':
            return victim_main()
        if mode == 'flapper':
            return flapper_main(sys.argv[i + 2])
        if mode == 'soak':
            return soak_main(sys.argv[i + 2])
        if mode == 'verify':
            return verify_main(sys.argv[i + 2], sys.argv[i + 3],
                               sys.argv[i + 4])
        raise SystemExit('unknown child mode %r' % mode)

    failures, stats = run_soak()
    if stats:
        print('CHAOS_STATS ' + json.dumps(stats, sort_keys=True))
    if failures:
        print('\ncheck_chaos FAILURES:')
        for f in failures:
            print('  - ' + f)
        return 1
    print('\ncheck_chaos OK: %d distinct fault kinds (worker kill, '
          'torn shard, %sheartbeat flap, collective stall) survived '
          'with zero intervention — %d recoveries, lost work %r '
          '(cadence %d), %d post-recovery steps bitwise-reproducible, '
          'every fault matched to a named supervisor decision in '
          '/statusz'
          % (stats['fault_kinds_injected'],
             'rpc fault, ' if stats['rpc_ok'] else '',
             stats['recoveries'], stats['lost_steps'], CADENCE,
             stats['bitwise_steps_verified']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
