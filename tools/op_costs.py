"""Per-instance op cost table + ranked kernel worklist (fluid.opprof).

Two modes:

* ``--url http://host:port`` scrapes a live job's ``/opprof`` endpoint
  (the health server replays its stashed snapshots server-side and
  returns report + worklist) and renders the tables — the operator's
  "where do this job's milliseconds go, by op?" one-liner.
* default (no --url): a self-contained demonstration run — LeNet +
  Adam through Executor.warmup with ``FLAGS_opprof`` on at snapshot
  cadence 1, eager replay of every stashed segment, the normalized
  per-instance table, and the worklist written to ``--out``
  (default op_worklist.json — the ROADMAP item 5 artifact).

Usage:
  python tools/op_costs.py [--steps N] [--out op_worklist.json]
  python tools/op_costs.py --url http://host:port [--out ...]
"""

import argparse
import json
import os
import sys


def render(rep, worklist, out=None):
    out = out if out is not None else sys.stdout
    out.write('%-26s %-22s %-10s %9s %9s %6s %10s\n'
              % ('instance', 'segment', 'layer', 'ms/step', 'raw_ms',
                 'calls', 'bytes'))
    for c in rep.get('top', []):
        out.write('%-26s %-22s %-10s %9.4f %9s %6d %10d\n'
                  % (c['instance'], c['segment'][:22],
                     c.get('layer') or '-', c['ms_per_step'],
                     '%.4f' % c['raw_ms'] if 'raw_ms' in c else '-',
                     c['calls'], c.get('bytes_per_step', 0)))
    unatt = rep.get('unattributed_ms')
    if unatt:
        out.write('unattributed: %.4f ms/step (honest remainder)\n'
                  % unatt)
    by_type = rep.get('by_type') or {}
    if by_type:
        out.write('by type:  %s\n' % ', '.join(
            '%s=%.3fms' % (t, v['ms_per_step'])
            for t, v in sorted(by_type.items(),
                               key=lambda kv: -kv[1]['ms_per_step'])[:8]))
    by_layer = rep.get('by_layer') or {}
    if by_layer:
        out.write('by layer: %s\n' % ', '.join(
            '%s=%.3fms' % (l, v)
            for l, v in sorted(by_layer.items(),
                               key=lambda kv: -kv[1])[:8]))
    if worklist:
        out.write('\nkernel worklist (contiguous same-type runs by '
                  'attributable cost):\n')
        for r in worklist:
            out.write('  #%d %-14s x%-3d %9.4f ms/step %12d B  '
                      '%s%s\n'
                      % (r['rank'], r['op_type'], len(r['ops']),
                         r['ms_per_step'], r['bytes_per_step'],
                         r['segment'][:24],
                         '  [covered by pallas/%s]' % r['covered_by']
                         if r.get('covered_by') else ''))


def scrape(url, out_path):
    import urllib.request
    with urllib.request.urlopen('%s/opprof' % url.rstrip('/'),
                                timeout=30) as resp:
        doc = json.loads(resp.read().decode('utf-8'))
    rep = doc.get('report') or {}
    worklist = doc.get('worklist') or []
    replayed = doc.get('replayed')
    if replayed:
        print('replayed %d stashed segment(s) server-side' %
              len(replayed))
    if doc.get('replay_error'):
        print('replay error (capture rows only): %s'
          % doc['replay_error'])
    render(rep, worklist)
    if out_path:
        with open(out_path, 'w') as f:
            json.dump({'version': 1, 'generated_by': 'fluid.opprof',
                       'candidates': worklist,
                       'by_type': rep.get('by_type'),
                       'by_layer': rep.get('by_layer'),
                       'segments': rep.get('segments')},
                      f, indent=2, sort_keys=True)
        print('kernel worklist written to %s' % out_path)
    return 0


def demo(steps, out_path):
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import opprof
    from paddle_tpu import models

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        feeds, pred, loss, acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(64, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (64, 1)).astype('int64')}

    fluid.set_flags({'FLAGS_opprof': True,
                     'FLAGS_opprof_snapshot_steps': 1})
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            exe.warmup(main_p,
                       feed_shapes={'img': ((64, 1, 28, 28), 'float32'),
                                    'label': ((64, 1), 'int64')},
                       fetch_list=[loss], wait=True)
            for _ in range(max(steps, 1)):
                exe.run(main_p, feed=feed, fetch_list=[loss])
            done = opprof.replay_all()
            print('replayed %d segment snapshot(s): %s\n'
                  % (len(done), ', '.join(
                      '%s=%s' % kv for kv in sorted(done.items()))))
            rep = opprof.report()
            worklist = opprof.kernel_worklist()
            render(rep, worklist)
            if out_path:
                opprof.write_worklist(out_path)
                print('kernel worklist written to %s' % out_path)
    finally:
        fluid.set_flags({'FLAGS_opprof': False,
                         'FLAGS_opprof_snapshot_steps': 16})
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--url', default=None,
                    help='scrape a live job: http://host:port of its '
                         'fluid.health status server (/opprof)')
    ap.add_argument('--steps', type=int, default=4,
                    help='demo mode: training steps before replay')
    ap.add_argument('--out', default='op_worklist.json',
                    help="worklist artifact path ('' skips writing)")
    args = ap.parse_args(argv)
    if args.url:
        return scrape(args.url, args.out)
    return demo(args.steps, args.out)


if __name__ == '__main__':
    sys.exit(main())
