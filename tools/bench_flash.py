"""Flash-vs-naive attention crossover bench.

Measures fwd+bwd wall time of the Pallas flash kernels against the
naive XLA chain at several sequence lengths on the attached TPU, for
BERT-base (h12 d64) and GPT/large shapes (d128) — round-4 VERDICT
item 7 widened the sweep beyond d=64.

Three columns per shape:
  naive    — the dense XLA chain
  flash    — the Pallas kernels, FORCED (min_seq=0)
  shipped  — the public flash_attention() auto-dispatch, which picks
             the dense path below FLASH_MIN_SEQ: this column must
             never lose to naive beyond noise.

Usage: python tools/bench_flash.py [--steps 30] [--block-sweep]
       [--dims 64 128] [--heads-for 64=12 128=16]
"""

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def naive_attention(q, k, v, causal=False):
    b, t, h, d = q.shape
    s = jnp.einsum('bthd,bshd->bhts', q, k,
                   preferred_element_type=jnp.float32) / (d ** 0.5)
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum('bhts,bshd->bthd', p, v)


def timed(fn, args, steps):
    """Chained steps (each consumes the previous grads) + one host
    readback: block_until_ready alone does not synchronize through the
    tunnel transport, so serialize on-device and sync via np.asarray
    (bench.py's convention)."""
    q, k, v = args

    def step(q, k, v):
        dq, dk, dv = fn(q, k, v)
        eps = jnp.bfloat16(1e-3)
        return (q + eps * dq.astype(q.dtype),
                k + eps * dk.astype(k.dtype),
                v + eps * dv.astype(v.dtype))

    step = jax.jit(step)
    q, k, v = step(q, k, v)
    np.asarray(q[0, 0, 0, 0].astype(jnp.float32))  # warm + sync
    t0 = time.perf_counter()
    for _ in range(steps):
        q, k, v = step(q, k, v)
    np.asarray(q[0, 0, 0, 0].astype(jnp.float32))
    return (time.perf_counter() - t0) / steps * 1e3


def loss_of(att):
    def f(q, k, v):
        return jnp.sum(att(q, k, v).astype(jnp.float32) ** 2)
    return jax.jit(jax.grad(f, argnums=(0, 1, 2)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=30)
    ap.add_argument('--batch', type=int, default=32)
    ap.add_argument('--heads', type=int, default=None,
                    help='override heads for every dim')
    ap.add_argument('--dims', type=int, nargs='+', default=[64, 128])
    ap.add_argument('--seqs', type=int, nargs='+',
                    default=[128, 256, 512, 1024, 2048])
    ap.add_argument('--causal', action='store_true')
    ap.add_argument('--block-sweep', action='store_true')
    args = ap.parse_args()

    from paddle_tpu.ops.pallas import flash_attention as fa

    rng = np.random.RandomState(0)
    # keep per-step FLOPs roughly comparable across dims: h12 for the
    # BERT shape, h16 d128 for the GPT/large shape at half the batch
    default_heads = {64: 12, 128: 16}
    default_batch = {64: args.batch, 128: max(1, args.batch // 2)}
    for dim in args.dims:
        heads = args.heads or default_heads.get(dim, 12)
        batch = default_batch.get(dim, args.batch)
        print('--- d=%d h=%d b=%d %s' % (dim, heads, batch,
              'causal' if args.causal else 'bidirectional'), flush=True)
        for t in args.seqs:
            shape = (batch, t, heads, dim)
            q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
            k = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
            v = jnp.asarray(rng.randn(*shape), jnp.bfloat16)

            g_naive = loss_of(functools.partial(naive_attention,
                                                causal=args.causal))
            ms_naive = timed(g_naive, (q, k, v), args.steps)

            g_flash = loss_of(functools.partial(
                fa.flash_attention, causal=args.causal, min_seq=0))
            ms_flash = timed(g_flash, (q, k, v), args.steps)

            g_ship = loss_of(functools.partial(fa.flash_attention,
                                               causal=args.causal))
            ms_ship = timed(g_ship, (q, k, v), args.steps)
            best = min(ms_naive, ms_flash)
            verdict = 'OK' if ms_ship <= best * 1.10 else \
                'SHIPPED LOSES'
            print('seq %5d  naive %7.2f  flash %7.2f  shipped %7.2f '
                  'ms  [%s]' % (t, ms_naive, ms_flash, ms_ship,
                                verdict), flush=True)

            if args.block_sweep:
                shipped = (fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K)
                seen = set()
                for bq in (128, 256, 512, 1024, 2048):
                    for bk in (128, 256, 512, 1024, 2048):
                        if bq > t or bk > t:
                            continue
                        # the VMEM clamp rewrites oversized configs;
                        # label (and dedupe) by what actually RUNS
                        ebq, ebk = fa._block_sizes(t, bq, bk, dim, 2)
                        if (ebq, ebk) in seen:
                            continue
                        seen.add((ebq, ebk))
                        fa.DEFAULT_BLOCK_Q = bq
                        fa.DEFAULT_BLOCK_K = bk
                        gf = loss_of(functools.partial(
                            fa.flash_attention, causal=args.causal,
                            min_seq=0))
                        ms = timed(gf, (q, k, v), args.steps)
                        print('    bq=%4d bk=%4d  %7.2f ms'
                              % (ebq, ebk, ms), flush=True)
                # restore SHIPPED defaults so later seqs measure them
                fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K = shipped


if __name__ == '__main__':
    main()
