"""Flash-vs-naive attention crossover bench (BERT-base shapes).

Measures fwd+bwd wall time of the Pallas flash kernels against the
naive XLA chain at several sequence lengths on the attached TPU.
Round-3 goal (VERDICT item 4): flash >= naive at seq 512 for d=64, or
roofline evidence it can't be on this chip.

Usage: python tools/bench_flash.py [--steps 30] [--block-sweep]
"""

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def naive_attention(q, k, v, causal=False):
    b, t, h, d = q.shape
    s = jnp.einsum('bthd,bshd->bhts', q, k,
                   preferred_element_type=jnp.float32) / (d ** 0.5)
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum('bhts,bshd->bthd', p, v)


def timed(fn, args, steps):
    """Chained steps (each consumes the previous grads) + one host
    readback: block_until_ready alone does not synchronize through the
    tunnel transport, so serialize on-device and sync via np.asarray
    (bench.py's convention)."""
    q, k, v = args

    def step(q, k, v):
        dq, dk, dv = fn(q, k, v)
        eps = jnp.bfloat16(1e-3)
        return (q + eps * dq.astype(q.dtype),
                k + eps * dk.astype(k.dtype),
                v + eps * dv.astype(v.dtype))

    step = jax.jit(step)
    q, k, v = step(q, k, v)
    np.asarray(q[0, 0, 0, 0].astype(jnp.float32))  # warm + sync
    t0 = time.perf_counter()
    for _ in range(steps):
        q, k, v = step(q, k, v)
    np.asarray(q[0, 0, 0, 0].astype(jnp.float32))
    return (time.perf_counter() - t0) / steps * 1e3


def loss_of(att):
    def f(q, k, v):
        return jnp.sum(att(q, k, v).astype(jnp.float32) ** 2)
    return jax.jit(jax.grad(f, argnums=(0, 1, 2)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=30)
    ap.add_argument('--batch', type=int, default=32)
    ap.add_argument('--heads', type=int, default=12)
    ap.add_argument('--dim', type=int, default=64)
    ap.add_argument('--seqs', type=int, nargs='+',
                    default=[128, 512, 2048])
    ap.add_argument('--causal', action='store_true')
    ap.add_argument('--block-sweep', action='store_true')
    args = ap.parse_args()

    from paddle_tpu.ops.pallas import flash_attention as fa

    rng = np.random.RandomState(0)
    for t in args.seqs:
        shape = (args.batch, t, args.heads, args.dim)
        q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
        k = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
        v = jnp.asarray(rng.randn(*shape), jnp.bfloat16)

        g_naive = loss_of(functools.partial(naive_attention,
                                            causal=args.causal))
        ms_naive = timed(g_naive, (q, k, v), args.steps)

        g_flash = loss_of(functools.partial(fa.flash_attention,
                                            causal=args.causal))
        ms_flash = timed(g_flash, (q, k, v), args.steps)
        print('seq %5d  naive %7.2f ms   flash %7.2f ms   (%s)'
              % (t, ms_naive, ms_flash,
                 'flash wins' if ms_flash < ms_naive else 'NAIVE wins'),
              flush=True)

        if args.block_sweep:
            shipped = (fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K)
            for bq in (128, 256, 512):
                for bk in (128, 256, 512):
                    if bq > t or bk > t:
                        continue
                    fa.DEFAULT_BLOCK_Q = bq
                    fa.DEFAULT_BLOCK_K = bk
                    gf = loss_of(functools.partial(
                        fa.flash_attention, causal=args.causal))
                    ms = timed(gf, (q, k, v), args.steps)
                    print('    bq=%3d bk=%3d  %7.2f ms' % (bq, bk, ms),
                          flush=True)
            # restore the SHIPPED defaults so later seqs measure them
            fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K = shipped


if __name__ == '__main__':
    main()
