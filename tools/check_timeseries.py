"""Telemetry-plane gate: the windowed time-series history, the SLO
burn-rate alerting and their HTTP surfaces must work against REAL
executors and REAL processes — and cost nothing when off.

Three postures:

  1. in-process live run: FLAGS_timeseries on, a real executor
     stepping a real program with the status plane on an ephemeral
     port.  /timeseries must serve a schema-valid directory listing,
     a counter window (executor/run_calls with derived reset-aware
     rate), a histogram window (executor/run_seconds with windowed
     p50/p95/p99), a `point` query, a 404-with-directory on an
     unknown name and a 400 on a malformed number; /statusz must
     carry the sparkline rollup section.  Then a deliberately-
     impossible SLO (`executor/run_seconds p99 < 1us`) is declared:
     it must walk ok -> pending -> firing through the hysteresis on
     the step cadence, show up under `firing` at /alertz with both
     burn-rate windows populated, and land a `slo_breach` decision in
     the supervisor decision log citing the breaching series;
  2. two-process job (tests/comms_worker.py x2, rank 0 aggregating
     with FLAGS_timeseries on): the aggregator's /timeseries must
     list both ranks in the job history, serve a per-worker
     (`?rank=1`) counter window built from scraped heartbeats, and
     serve its own local series — per-worker AND aggregated history
     from one endpoint;
  3. disabled-path cost: with FLAGS_timeseries off (the default),
     tools/check_hot_path.py's steady-state budgets must still hold —
     the step boundary pays one flag read for the whole plane.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:   # 4xx bodies are part of
        return e.code, e.read()           # the surface under test


def _get_json(url, timeout=10):
    code, body = _get(url, timeout=timeout)
    return code, json.loads(body)


def _wait_ready(proc, url, deadline):
    while time.time() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read().decode('utf-8', 'replace') \
                if proc.stdout else ''
            raise RuntimeError('worker died rc=%d: %s'
                               % (proc.returncode, out[-800:]))
        try:
            code, _ = _get(url + '/healthz/local', timeout=2)
            if code == 200:
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise RuntimeError('worker at %s never became ready' % url)


def check_local_plane(failures):
    """Posture 1: live in-process run against the real status plane."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, slo, supervisor, timeseries

    port = _free_port()
    # aggressive windows so the hysteresis walk fits in a short run
    fluid.set_flags({'FLAGS_timeseries': True,
                     'FLAGS_status_port': port,
                     'FLAGS_slo_fast_points': 4,
                     'FLAGS_slo_slow_points': 8,
                     'FLAGS_slo_hysteresis': 2})
    timeseries.reset()
    slo.reset()
    supervisor.reset()

    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(prog, startup):
        x = layers.data('x', shape=[16], dtype='float32')
        h = layers.fc(x, 16, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    feed = {'x': np.ones((4, 16), 'float32')}
    base = 'http://127.0.0.1:%d' % port
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for _ in range(20):
                exe.run(prog, feed=feed, fetch_list=[loss])

            # directory listing
            code, doc = _get_json(base + '/timeseries')
            if code != 200 or not doc.get('enabled') \
                    or 'executor/run_seconds' not in doc.get(
                        'series', []):
                failures.append('/timeseries listing broken: code=%d '
                                'enabled=%r series~%d'
                                % (code, doc.get('enabled'),
                                   len(doc.get('series', []))))

            # counter window: derived reset-aware rate over real steps
            code, doc = _get_json(
                base + '/timeseries?name=executor/run_calls&points=16')
            if code != 200 or doc.get('kind') != 'counter':
                failures.append('counter window broken: %d %r'
                                % (code, doc.get('kind')))
            else:
                d = doc['derived']
                if not (doc['n'] >= 2 and d['rate_per_s'] and
                        d['rate_per_s'] > 0 and
                        d['total_delta'] > 0 and d['resets'] == 0):
                    failures.append('counter derived math wrong: %r'
                                    % d)
                if len(doc['points'][0]) != 3:
                    failures.append('counter point is not '
                                    '(ts, step, value): %r'
                                    % doc['points'][0])

            # histogram window: windowed percentiles from cumulative
            # bucket subtraction
            code, doc = _get_json(
                base + '/timeseries?name=executor/run_seconds'
                       '&points=16')
            if code != 200 or doc.get('kind') != 'hist':
                failures.append('hist window broken: %d %r'
                                % (code, doc.get('kind')))
            else:
                d = doc['derived']
                pcts = d.get('percentiles', {})
                if not (d['count'] > 0 and d['sum'] > 0 and
                        pcts.get('p50') is not None and
                        pcts.get('p99') is not None and
                        pcts['p50'] <= pcts['p99']):
                    failures.append('hist window percentiles wrong: '
                                    '%r' % d)
                if not doc.get('edges'):
                    failures.append('hist window lost its edges')

            # point query + error surfaces
            code, doc = _get_json(
                base + '/timeseries?name=executor/run_calls&point=1')
            if code != 200 or len(doc.get('point', [])) != 3:
                failures.append('point query broken: %d %r'
                                % (code, doc.get('point')))
            code, doc = _get_json(base + '/timeseries?name=no/such')
            if code != 404 or not doc.get('series'):
                failures.append('unknown series should 404 with the '
                                'directory, got %d' % code)
            code, doc = _get_json(
                base + '/timeseries?name=executor/run_calls'
                       '&points=banana')
            if code != 400:
                failures.append('malformed points= should 400, got '
                                '%d' % code)

            # /statusz sparkline rollup
            code, body = _get(base + '/statusz')
            ts_sec = json.loads(body).get('timeseries')
            if not ts_sec or not ts_sec.get('series'):
                failures.append('/statusz timeseries section missing '
                                'or empty')
            elif not any(r.get('spark') for r in ts_sec['series']):
                failures.append('/statusz timeseries rows carry no '
                                'sparklines: %r' % ts_sec['series'][:2])

            # seeded SLO breach: impossible latency target must walk
            # the hysteresis to firing on the step cadence
            slo.declare('executor/run_seconds p99 < 1us',
                        name='seeded_latency')
            for _ in range(12):
                exe.run(prog, feed=feed, fetch_list=[loss])
            code, doc = _get_json(base + '/alertz')
            firing = {a['name']: a for a in doc.get('firing', [])}
            if 'seeded_latency' not in firing:
                failures.append(
                    '/alertz: seeded SLO never fired (firing=%r '
                    'pending=%r)' % (sorted(firing),
                                     [a['name'] for a in
                                      doc.get('pending', [])]))
            else:
                a = firing['seeded_latency']
                if not (a.get('burn_fast') and a.get('burn_fast') > 1
                        and a.get('burn_slow') and
                        a.get('measured_fast') is not None and
                        a.get('window', {}).get('fast_points') == 4):
                    failures.append('/alertz firing doc missing burn '
                                    'windows: %r' % a)

            # the supervisor decision log must cite the breach
            recs = [d for d in supervisor.decisions()
                    if d.get('kind') == 'slo_breach']
            if not recs:
                failures.append('no slo_breach decision recorded in '
                                'the supervisor log')
            else:
                info = recs[-1].get('info', {})
                if info.get('series') != 'executor/run_seconds' or \
                        not info.get('window'):
                    failures.append('slo_breach decision does not '
                                    'cite series+window: %r' % info)
    finally:
        fluid.set_flags({'FLAGS_timeseries': False,
                         'FLAGS_slo_fast_points': 12,
                         'FLAGS_slo_slow_points': 96,
                         'FLAGS_slo_hysteresis': 3})
        slo.reset()
        supervisor.reset()
        timeseries.reset()


def check_job_plane(failures):
    """Posture 2: two real processes, rank 0 aggregating per-worker
    history from scraped heartbeats."""
    worker = os.path.join(ROOT, 'tests', 'comms_worker.py')
    p0, p1 = _free_port(), _free_port()
    spec = '0=127.0.0.1:%d,1=127.0.0.1:%d' % (p0, p1)
    base_env = dict(os.environ)
    base_env.update({'PADDLE_TPU_STATUS_WORKERS': spec,
                     'FLAGS_health_heartbeat_seconds': '0.5',
                     'FLAGS_timeseries': '1'})
    env0 = dict(base_env, PADDLE_TRAINER_ID='0',
                PADDLE_TPU_STATUS_AGGREGATE='1')
    env1 = dict(base_env, PADDLE_TRAINER_ID='1',
                PADDLE_TPU_STATUS_AGGREGATE='0')
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p1), '120'], env=env1,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p0), '120'], env=env0,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        deadline = time.time() + 240
        agg = 'http://127.0.0.1:%d' % p0
        wrk = 'http://127.0.0.1:%d' % p1
        _wait_ready(procs[0], wrk, deadline)
        _wait_ready(procs[1], agg, deadline)
        # let a few heartbeats land so per-rank series have >= 2
        # points (rates need pairs)
        time.sleep(2.5)

        code, doc = _get_json(agg + '/timeseries')
        ranks = doc.get('ranks', [])
        if code != 200 or not ('0' in ranks and '1' in ranks):
            failures.append('aggregator job history covers ranks %r, '
                            'wanted 0 and 1' % ranks)
        if doc.get('job_samples', 0) < 4:
            failures.append('aggregator retained only %r job samples '
                            'after 2.5s of 0.5s heartbeats'
                            % doc.get('job_samples'))

        # a per-worker series scraped over heartbeats, windowed
        code, doc = _get_json(
            agg + '/timeseries?rank=1&name=executor/run_calls'
                  '&points=32')
        if code != 200 or doc.get('kind') != 'counter' or \
                doc.get('rank') != '1':
            failures.append('per-worker window broken: %d kind=%r '
                            'rank=%r' % (code, doc.get('kind'),
                                         doc.get('rank')))
        elif not (doc['n'] >= 2 and
                  doc['derived']['total_delta'] > 0):
            failures.append('rank-1 run_calls never advanced across '
                            'heartbeats: %r' % doc['derived'])

        # the aggregator's own local history serves from the same
        # endpoint (no rank param)
        code, doc = _get_json(
            agg + '/timeseries?name=executor/run_calls&points=32')
        if code != 200 or doc.get('rank') is not None or doc['n'] < 2:
            failures.append('aggregator local series broken: %d '
                            'rank=%r n=%r' % (code, doc.get('rank'),
                                              doc.get('n')))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    sys.path.insert(0, ROOT)
    failures = []

    check_local_plane(failures)
    check_job_plane(failures)

    # ---- 3: disabled-path hot-loop budgets ------------------------------
    env = dict(os.environ)
    env.pop('FLAGS_timeseries', None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools',
                                      'check_hot_path.py')],
        env=env, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        failures.append('check_hot_path budgets broke with the '
                        'timeseries hook in the step loop:\n%s'
                        % (r.stdout + r.stderr)[-800:])

    if failures:
        print('check_timeseries: FAIL')
        for f in failures:
            print('  - %s' % f)
        return 1
    print('check_timeseries: /timeseries windows schema-valid '
          '(counter rate, hist percentiles, point/404/400), /statusz '
          'sparklines render, seeded SLO fired at /alertz + cited in '
          'the supervisor decision log, 2-rank job history serves '
          'per-worker and aggregated series, hot-path budgets hold')
    return 0


if __name__ == '__main__':
    sys.exit(main())
