"""Measure the pure jit-boundary cost of the BERT-long train step's
state pytree: a donated identity jit over the SAME state dict
(464 arrays on BERT-base), timed like the step.  If identity costs ~0 ms the 10% gap vs the
hand-JAX ceiling is in the compiled program (kernel scheduling); if it
costs milliseconds, the boundary (argument/donation processing per
array) is the lever and state-packing is the fix.

Usage: python tools/boundary_cost.py [--batch 4 --seq 2048 --steps 20]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))



def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--batch', type=int, default=4)
    ap.add_argument('--seq', type=int, default=2048)
    ap.add_argument('--steps', type=int, default=20)
    args = ap.parse_args()

    import jax
    from bert_long_common import build_train_segment
    state = build_train_segment(args.batch, args.seq)['state']
    n_arrays = len(state)
    n_bytes = sum(getattr(v, 'nbytes', 0) for v in state.values())
    print('state: %d arrays, %.1f MB' % (n_arrays, n_bytes / 1e6))

    @jax.jit
    def ident(state):
        return {k: v for k, v in state.items()}

    ident_d = jax.jit(lambda s: {k: v for k, v in s.items()},
                      donate_argnums=(0,))

    for name, fn in (('identity        ', ident),
                     ('identity+donate ', ident_d)):
        st = jax.tree.map(jax.device_put, state)
        st = fn(st)  # warm
        jax.block_until_ready(st)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            st = fn(st)
        jax.block_until_ready(st)
        dt = (time.perf_counter() - t0) / args.steps * 1e3
        print('%s: %.2f ms/call' % (name, dt))


if __name__ == '__main__':
    main()
