"""Audit: every registered op must be exercised by the test suite.

The analog of the reference's CI gates (tools/check_op_desc.py,
check_api_approvals.sh): regressions that add an op without a test
fail this check.  'Exercised' is name-level — the op type appears in
some tests/*.py — which is deliberately the weakest signature that
still catches silently-untested additions; the sweeps
(test_grad_check_sweep*.py, test_op_sweep3.py, test_ops_*.py) carry
the behavioral depth.

Exit 0 when every op is referenced; prints the missing list and exits
1 otherwise.
"""

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def untested_ops(repo_root=None):
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import paddle_tpu.fluid  # noqa: F401 — triggers op registration
    from paddle_tpu.ops import registry
    root = repo_root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    text = ''
    for f in glob.glob(os.path.join(root, 'tests', '*.py')):
        with open(f) as fh:
            text += fh.read()
    ops = sorted(registry._REGISTRY.keys())
    # grad ops are synthesized from their forward op's vjp; the sweep
    # exercises them through append_backward, not by name
    return [o for o in ops if not o.endswith('_grad') and o not in text]


def main():
    missing = untested_ops()
    total = len(missing)
    if missing:
        print('%d registered ops are not referenced by any test:'
              % total)
        for o in missing:
            print(' ', o)
        return 1
    print('test coverage audit: every registered op is referenced by '
          'the suite')
    return 0


if __name__ == '__main__':
    sys.exit(main())
