"""Capture a device profile of ANY bench workload and print the kernel
rollup (generalizes tools/profile_resnet.py to the whole bench suite).

Usage: python tools/profile_step.py bert [kwargs as k=v ...]
       python tools/profile_step.py transformer steps=8
Workloads: any bench_<name> in bench.py (resnet50, lenet, bert,
bert_long, wide_deep, transformer, ...).

The trace wraps the bench call, so warmup/compile appear in the module
span but barely perturb the kernel rollup (steady-state steps
dominate).  Raw trace under /tmp/paddle_tpu_profile_step for
TensorBoard/Perfetto.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import bench
    from profile_resnet import analyze

    name = sys.argv[1] if len(sys.argv) > 1 else 'bert'
    kwargs = {}
    for arg in sys.argv[2:]:
        k, v = arg.split('=', 1)
        try:
            kwargs[k] = int(v)
        except ValueError:
            kwargs[k] = v
    fn = getattr(bench, 'bench_' + name)
    logdir = '/tmp/paddle_tpu_profile_step'
    os.system('rm -rf %s' % logdir)
    # the trace hook in bench._timed_steps covers ONLY the steady-state
    # loop — wrapping the whole call (incl. compile) floods the 1M
    # host-event cap and the device plane is dropped.  Workloads with
    # their own timing loop (resnet50, resnet_infer) take the full
    # wrap; decided upfront so nothing runs twice.
    import inspect
    uses_hook = '_timed_steps' in inspect.getsource(fn)
    if uses_hook:
        bench.TRACE_LOGDIR = logdir
        try:
            result = fn(**kwargs)
        finally:
            bench.TRACE_LOGDIR = None
    else:
        with jax.profiler.trace(logdir):
            result = fn(**kwargs)
    print(result)
    import inspect
    default_steps = inspect.signature(fn).parameters['steps'].default
    analyze(logdir, kwargs.get('steps', default_steps))


if __name__ == '__main__':
    main()
