"""Health-plane gate: the status endpoints must serve a REAL process's
data correctly, and the opt-in tensor-health summaries must cost
nothing when off (the fluid.health analog of check_trace.py's gate).

Runs one in-process sequence:

  1. boot a real executor, train a tiny program, start the status
     server on an ephemeral port, and curl /healthz //metrics
     //statusz //trace/dump: /metrics must pass the fluid.health
     prom_lint (HELP/TYPE per family, no duplicate series, histogram
     bucket consistency), /healthz must report ready with recent step
     age, /statusz must carry the step report / cache stats / flags /
     versions schema;
  2. FLAGS_health_summaries on: a fresh program's steps must record
     the health/* histograms (grad norm, update ratio, global grad
     norm) with zero summary errors;
  3. FLAGS_health_summaries off (the default posture): the
     steady-state hot-path budgets of tools/check_hot_path.py must
     still hold — the "opt-in costs nothing when off" claim.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import sys
import urllib.error
import urllib.request


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode('utf-8')
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode('utf-8')


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import health, layers, monitor, trace

    failures = []

    def build(seed=5):
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = seed
        with fluid.program_guard(main_p, startup):
            x = layers.data('x', shape=[16], dtype='float32')
            h = layers.fc(x, 16)
            loss = layers.reduce_mean(layers.square(h))
            fluid.optimizer.SGD(0.01).minimize(loss)
        return main_p, startup, loss

    # -- 1. endpoints over a live executor ---------------------------
    main_p, startup, loss = build()
    feed = {'x': np.ones((8, 16), 'float32')}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(3):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        trace.enable(buffer_steps=8)
        exe.run(main_p, feed=feed, fetch_list=[loss])
        srv = health.serve(port=0)
        try:
            code, text = _get(srv.url + '/metrics')
            problems = health.prom_lint(text)
            if code != 200:
                failures.append('/metrics returned %d' % code)
            if problems:
                failures.append('/metrics lint: %s'
                                % '; '.join(problems[:5]))
            if 'paddle_tpu_executor_run_calls' not in text:
                failures.append('/metrics missing executor counters')

            code, body = _get(srv.url + '/healthz')
            doc = json.loads(body)
            if code != 200 or not doc.get('ready'):
                failures.append('/healthz not ready on a stepping '
                                'process: %d %r' % (code, doc))
            for key in ('alive', 'ready', 'steps', 'last_step_age_s',
                        'pid', 'uptime_s'):
                if key not in doc:
                    failures.append('/healthz missing %r' % key)

            code, body = _get(srv.url + '/statusz')
            doc = json.loads(body)
            if code != 200:
                failures.append('/statusz returned %d' % code)
            if 'rollup' not in doc.get('step_report', {}):
                failures.append('/statusz missing step_report.rollup')
            if 'segment_cache_hit' not in doc.get('caches', {}):
                failures.append('/statusz missing cache stats')
            if 'FLAGS_health_summaries' not in doc.get('flags', {}):
                failures.append('/statusz missing flags')
            if not doc.get('versions', {}).get('jax'):
                failures.append('/statusz missing jax version')

            code, body = _get(srv.url + '/trace/dump')
            doc = json.loads(body)
            if code != 200 or not doc.get('ptSteps'):
                failures.append('/trace/dump empty on a traced step')
            elif not os.path.exists(doc.get('ptDumpPath', '')):
                failures.append('/trace/dump wrote no file')
            print('endpoints: /metrics %dB lint-clean, healthz ready, '
                  'statusz schema ok, trace dump %d steps'
                  % (len(text), len(doc.get('ptSteps', []))))
        finally:
            srv.stop()
    trace.disable()
    trace.reset()

    # -- 2. summaries on: health histograms recorded -----------------
    fluid.set_flags({'FLAGS_health_summaries': True})
    health.reset_state()
    try:
        main2, startup2, loss2 = build(seed=6)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup2)
            for _ in range(4):
                exe.run(main2, feed=feed, fetch_list=[loss2])
        for name in ('health/grad_norm', 'health/update_ratio',
                     'health/global_grad_norm'):
            h = monitor.histogram_value(name)
            if not h or h['count'] < 4:
                failures.append('summaries on: %s not recorded (%r)'
                                % (name, h))
        errs = monitor.counter_value('health/summary_errors')
        if errs:
            failures.append('summaries on: %g summary errors' % errs)
        print('summaries: %d steps, global grad norm %.4f'
              % (int(monitor.counter_value('health/summary_steps')),
                 monitor.gauge_value('health/last_global_grad_norm')))
    finally:
        fluid.set_flags({'FLAGS_health_summaries': False})
        health.reset_state()

    # -- 3. summaries off: hot-path budgets unchanged ----------------
    monitor.reset()
    sys.path.insert(0, os.path.join(root, 'tools'))
    import check_hot_path
    rc = check_hot_path.main()
    if rc != 0:
        failures.append('check_hot_path budgets violated with health '
                        'summaries disabled (rc=%d)' % rc)

    if failures:
        for f in failures:
            print('HEALTH GATE  ' + f)
        return 1
    print('health plane: ok')
    return 0


if __name__ == '__main__':
    sys.exit(main())
