"""Self-healing supervisor gate: a REAL two-process job must survive a
kill -9 of one worker with ZERO human intervention — the rank-0
supervisor confirms the death through the health aggregator's
consecutive-miss signal, prices the reshard, degrades to the survivor
INSIDE the rejoin-wait budget, and the post-recovery trajectory holds
loss parity with an uninterrupted run resumed from the same
checkpoint.

Note on topology: cross-process jax collectives are unavailable on
this container's CPU backend (the known env-level limitation the
tier-1 suite documents), so "the job" is the suite's standard
cluster-in-a-box posture: worker 0 (the survivor, rank 0 aggregator +
supervisor) trains on its own virtual devices while worker 1 is a live
peer process on the status plane.  Every death, scrape and recovery
crosses a REAL OS process boundary — which is exactly what the
controller gates.

Phases:

  1. worker 1 (the victim) comes up with a status server and a slow
     train loop, armed with 'executor.step:die@N' — a real kill -9
     mid-step (os._exit(9), no teardown);
  2. worker 0 trains with the supervisor attached (periodic
     checkpoints on cadence, the aggregator scraping worker 1); the
     victim dies mid-soak; the supervisor must confirm the death
     within FLAGS_heartbeat_misses scrapes, decide (priced reshard vs
     rejoin budget), recover from last-good and finish the run;
  3. a fresh verifier process resumes the SAME generation the
     recovery loaded and replays to the same target step: every
     post-recovery loss must match BITWISE.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TARGET_STEP = 16
CADENCE = 3
HEARTBEAT_S = 0.25
MISSES = 2
REJOIN_WAIT_S = 8.0


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def build_model():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import unique_name
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            y = fluid.layers.data('y', shape=[1], dtype='float32')
            h = fluid.layers.fc(x, 16, act='relu')
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.reduce_mean(fluid.layers.square(
                fluid.layers.elementwise_sub(pred, y)))
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def batch_for(step, n=8):
    import numpy as np
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(n, 8).astype('float32')
    return x, (x.sum(1, keepdims=True) * 0.5).astype('float32')


def _hex(v):
    import numpy as np
    return np.float32(np.asarray(v).ravel()[0]).tobytes().hex()


def victim_main():
    """Worker 1: a live status-plane peer that dies by kill -9
    (faultinject executor.step:die) mid-step."""
    import paddle_tpu.fluid as fluid
    main, startup, loss = build_model()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))   # auto-serves status
        exe.run(startup)
        # stay visibly UP long enough for the aggregator's first
        # scrapes: a death is only confirmable for a worker that WAS up
        time.sleep(1.0)
        for s in range(1000):
            x, y = batch_for(s)
            exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
            time.sleep(0.1)
    print('VICTIM_SURVIVED')     # the die clause must prevent this


def survivor_main(store):
    """Worker 0: rank-0 aggregator + supervisor; trains through the
    victim's death with zero intervention."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import monitor, supervisor
    main, startup, loss = build_model()
    losses = {}
    recoveries = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        x0, y0 = batch_for(0)
        supervisor.attach(store, program=main, executor=exe,
                          checkpoint_steps=CADENCE,
                          rejoin_wait_s=REJOIN_WAIT_S,
                          feed_shapes={'x': x0, 'y': y0},
                          fetch_list=[loss])
        deadline = time.time() + 60
        target = TARGET_STEP
        try:
            while time.time() < deadline:
                s = int(exe._step)
                if s >= target and recoveries:
                    break
                x, y = batch_for(s)
                try:
                    l, = exe.run(main, feed={'x': x, 'y': y},
                                 fetch_list=[loss])
                    losses[int(exe._step)] = _hex(l)
                except supervisor.Recovered as e:
                    recoveries.append({
                        'generation': e.generation, 'step': e.step,
                        'lost_steps': e.lost_steps,
                        'wall': time.time()})
                    # the parity leg needs a post-recovery trajectory:
                    # always train several steps past the resume point
                    target = max(TARGET_STEP, e.step + 6)
                    continue
                time.sleep(0.12)
        finally:
            decs = supervisor.decisions()
            sup = supervisor.current()
            t = sup._save_thread if sup else None
            supervisor.detach()
            if t is not None:
                t.join(timeout=10)
    out = {
        'losses': losses,
        'recoveries': recoveries,
        'final_step': int(exe._step),
        'decisions': [{k: d.get(k) for k in
                       ('kind', 'choice', 'acted', 'wall_unix',
                        'info')} for d in decs],
        'deaths_confirmed': monitor.counter_value(
            'supervisor/deaths_confirmed'),
        'recoveries_count': monitor.counter_value(
            'supervisor/recoveries'),
        'checkpoints': monitor.counter_value(
            'supervisor/checkpoints_taken'),
    }
    print('CHECK_JSON ' + json.dumps(out))


def verify_main(store, generation, target):
    """Uninterrupted run resumed from the SAME generation the
    recovery loaded: the parity reference."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import elastic
    main, startup, loss = build_model()
    losses = {}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        elastic.load_checkpoint(store, main, executor=exe,
                                generation=int(generation))
        while exe._step < int(target):
            s = int(exe._step)
            x, y = batch_for(s)
            l, = exe.run(main, feed={'x': x, 'y': y},
                         fetch_list=[loss])
            losses[int(exe._step)] = _hex(l)
    print('CHECK_JSON ' + json.dumps({'losses': losses}))


# ------------------------------------------------------------- driver
def _spawn(mode, args, extra_env=None, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), '--child', mode]
        + [str(a) for a in args],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _child_json(stdout, proc=None, tag=''):
    for line in stdout.splitlines():
        if line.startswith('CHECK_JSON '):
            return json.loads(line[len('CHECK_JSON '):])
    raise RuntimeError('%s produced no CHECK_JSON\n%s' % (tag,
                                                          stdout[-2000:]))


def main():
    if '--child' in sys.argv:
        i = sys.argv.index('--child')
        sys.path.insert(0, REPO)
        mode = sys.argv[i + 1]
        if mode == 'victim':
            return victim_main()
        if mode == 'survivor':
            return survivor_main(sys.argv[i + 2])
        if mode == 'verify':
            return verify_main(sys.argv[i + 2], sys.argv[i + 3],
                               sys.argv[i + 4])
        raise SystemExit('unknown child mode %r' % mode)

    import numpy as np  # noqa: F401 — env sanity before subprocesses
    work = tempfile.mkdtemp(prefix='pt_supervisor_check_')
    store = os.path.join(work, 'store')
    p0, p1 = _free_port(), _free_port()
    spec = '0=127.0.0.1:%d,1=127.0.0.1:%d' % (p0, p1)
    common = {
        'PADDLE_TPU_STATUS_WORKERS': spec,
        'FLAGS_health_heartbeat_seconds': str(HEARTBEAT_S),
        'FLAGS_heartbeat_misses': str(MISSES),
        'FLAGS_trace': '1',
        'FLAGS_elastic_keep_generations': '32',
    }
    failures = []
    victim = survivor = None
    try:
        # worker 1: status server up, then a real kill -9 mid-step
        victim = _spawn('victim', [], dict(
            common, PADDLE_TRAINER_ID='1', FLAGS_status_port=str(p1),
            FLAGS_faultinject='executor.step:die@6'))
        # worker 0: aggregator + supervisor, trains through the death
        survivor = _spawn('survivor', [store], dict(
            common, PADDLE_TRAINER_ID='0', FLAGS_status_port=str(p0)))
        s_out, s_err = survivor.communicate(timeout=240)
        v_rc = victim.wait(timeout=60)
        if v_rc != 9:
            failures.append('victim exited %r, wanted the kill -9 '
                            'code 9' % v_rc)
        if survivor.returncode != 0:
            failures.append('survivor exited %d\n%s'
                            % (survivor.returncode, s_err[-2000:]))
        res = _child_json(s_out, tag='survivor')
        kinds = [(d['kind'], d['choice']) for d in res['decisions']]
        print('survivor: %d decisions, %d checkpoints, %d recoveries, '
              'final step %d'
              % (len(kinds), res['checkpoints'],
                 res['recoveries_count'], res['final_step']))

        if res['deaths_confirmed'] < 1:
            failures.append('the victim death was never confirmed')
        if not any(k == 'death' for k, _c in kinds):
            failures.append('no death decision logged: %r' % kinds)
        if res['recoveries_count'] < 1 or not res['recoveries']:
            failures.append('the supervisor never recovered')
        if res['final_step'] < TARGET_STEP:
            failures.append('survivor stopped at step %d < target %d'
                            % (res['final_step'], TARGET_STEP))

        # recovery inside the rejoin-wait budget: confirmed-death
        # decision -> recovered decision wall delta
        death_wall = next((d['wall_unix'] for d in res['decisions']
                           if d['kind'] == 'death'), None)
        rec_wall = next((d['wall_unix'] for d in res['decisions']
                         if d['kind'] == 'recovery' and
                         d['choice'] == 'recovered'), None)
        if death_wall is None or rec_wall is None:
            failures.append('death/recovery decisions missing from '
                            'the log')
        else:
            within = rec_wall - death_wall
            print('death -> recovery in %.2fs (budget %.1fs)'
                  % (within, REJOIN_WAIT_S))
            if within > REJOIN_WAIT_S:
                failures.append('recovery took %.2fs, beyond the '
                                '%.1fs rejoin budget'
                                % (within, REJOIN_WAIT_S))

        # bounded lost work
        for r in res['recoveries']:
            if r['lost_steps'] > CADENCE:
                failures.append('recovery lost %d steps > cadence %d'
                                % (r['lost_steps'], CADENCE))

        # loss parity vs an uninterrupted run from the same checkpoint
        if res['recoveries']:
            last = res['recoveries'][-1]
            target = max(int(s) for s in res['losses'])
            verify = _spawn('verify',
                            [store, last['generation'], target])
            vout, verr = verify.communicate(timeout=240)
            if verify.returncode != 0:
                failures.append('verifier exited %d\n%s'
                                % (verify.returncode, verr[-2000:]))
            else:
                ref = _child_json(vout, tag='verify')['losses']
                compared = 0
                for s, hx in ref.items():
                    if int(s) <= last['step']:
                        continue
                    got = res['losses'].get(s)
                    if got is None:
                        continue
                    compared += 1
                    if got != hx:
                        failures.append(
                            'step %s diverged from the uninterrupted '
                            'resume: %s vs %s' % (s, got, hx))
                print('parity: %d post-recovery steps bitwise-equal '
                      'to the uninterrupted resume from gen %s'
                      % (compared, last['generation']))
                if compared < 3:
                    failures.append('only %d post-recovery steps '
                                    'compared' % compared)
    finally:
        for p in (victim, survivor):
            if p is not None and p.poll() is None:
                try:
                    p.send_signal(signal.SIGKILL)
                except OSError:
                    pass
        shutil.rmtree(work, ignore_errors=True)

    if failures:
        print('\ncheck_supervisor FAILURES:')
        for f in failures:
            print('  - ' + f)
        return 1
    print('\ncheck_supervisor OK: kill -9 of a worker confirmed '
          'through the aggregator, supervisor degraded to the '
          'survivor inside the rejoin budget, lost work bounded by '
          'the checkpoint cadence, post-recovery trajectory '
          'bitwise-equal to an uninterrupted resume')
    return 0


if __name__ == '__main__':
    sys.exit(main())
