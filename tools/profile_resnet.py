"""Capture a device profile of the ResNet-50 bench step and print the
op-level time breakdown.

Usage: python tools/profile_resnet.py [NHWC|NCHW] [batch]

Writes the raw trace under /tmp/paddle_tpu_profile (TensorBoard/Perfetto
format, from jax.profiler) and prints the top XLA ops by self-time parsed
from the trace.json.gz so the bottleneck is visible without a UI.
"""

import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from collections import defaultdict

import numpy as np


def run_profiled(layout='NHWC', batch=128, steps=6):
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, logits, loss, acc = models.resnet.build(data_format=layout)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Momentum(0.1, momentum=0.9),
            use_dynamic_loss_scaling=True)
        opt.minimize(loss)

    rng = np.random.RandomState(0)
    shape = (batch, 224, 224, 3) if layout == 'NHWC' else \
        (batch, 3, 224, 224)
    x = jax.device_put(rng.rand(*shape).astype('float32'))
    y = jax.device_put(rng.randint(0, 1000, (batch, 1)).astype('int32'))

    logdir = '/tmp/paddle_tpu_profile'
    os.system('rm -rf %s' % logdir)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={'image': x, 'label': y}, fetch_list=[])
        l, = exe.run(main, feed={'image': x, 'label': y},
                     fetch_list=[loss])
        np.asarray(l)
        with jax.profiler.trace(logdir):
            for _ in range(steps):
                exe.run(main, feed={'image': x, 'label': y},
                        fetch_list=[])
            l, = exe.run(main, feed={'image': x, 'label': y},
                         fetch_list=[loss])
            np.asarray(l)
    return logdir, steps + 1


def analyze(logdir, steps):
    paths = glob.glob(logdir + '/**/*.trace.json.gz', recursive=True)
    if not paths:
        print('no trace.json.gz found under', logdir)
        print('files:', glob.glob(logdir + '/**/*', recursive=True)[:20])
        return
    path = sorted(paths)[-1]
    with gzip.open(path, 'rt') as f:
        trace = json.load(f)
    events = trace.get('traceEvents', [])
    # device-lane complete events: aggregate self time by op name
    by_name = defaultdict(float)
    count = defaultdict(int)
    pid_names = {}
    for e in events:
        if e.get('ph') == 'M' and e.get('name') == 'process_name':
            pid_names[e.get('pid')] = e.get('args', {}).get('name', '')
    device_pids = set(p for p, n in pid_names.items()
                      if 'TPU' in n or 'Device' in n or 'XLA' in n
                      or '/device' in n.lower())
    for e in events:
        if e.get('ph') != 'X':
            continue
        if device_pids and e.get('pid') not in device_pids:
            continue
        name = e.get('name', '?')
        by_name[name] += e.get('dur', 0)
        count[name] += 1
    # the step-level spans (whole-module executions) double-count the
    # kernels inside them: split them out
    import re
    step_spans = {}
    kernels = {}
    for name, us in by_name.items():
        if name.startswith('jit_') or re.fullmatch(r'\d+', name):
            step_spans[name] = us
        else:
            kernels[name] = us
    total = sum(kernels.values())
    module_time = sum(us for n, us in step_spans.items()
                      if n.startswith('jit_'))
    print('process lanes:', sorted(set(pid_names.values())))
    print('module span: %.1f ms; kernel busy: %.1f ms (%.0f%% busy) '
          'across %d distinct kernels, ~%d launches/step'
          % (module_time / 1e3, total / 1e3,
             100.0 * total / max(module_time, 1),
             len(kernels), sum(count[n] for n in kernels) // steps))
    # category rollup: strip trailing .N / digits
    cats = defaultdict(float)
    for name, us in kernels.items():
        cat = re.sub(r'[.\d]+$', '', name)
        cats[cat] += us
    print('\n-- by category --')
    for name, us in sorted(cats.items(), key=lambda kv: -kv[1])[:20]:
        print('%-48s %10.2f ms %5.1f%%'
              % (name[:48], us / 1e3, 100.0 * us / max(total, 1)))
    print('\n-- top kernels --')
    print('%-64s %10s %6s %6s' % ('op', 'ms', 'count', '%'))
    for name, us in sorted(kernels.items(), key=lambda kv: -kv[1])[:30]:
        print('%-64s %10.2f %6d %5.1f%%'
              % (name[:64], us / 1e3, count[name],
                 100.0 * us / max(total, 1)))


if __name__ == '__main__':
    layout = sys.argv[1] if len(sys.argv) > 1 else 'NHWC'
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    logdir, steps = run_profiled(layout, batch)
    analyze(logdir, steps)
