"""Anchor the bench's model-derived MFU with a TRACE-derived one.

VERDICT r4 weak #6: `bench.py`'s `tflops`/`mfu_pct`/`hbm_pct` come
from XLA cost analysis (`Executor.program_cost`) — a model, not a
measurement ("bytes accessed" counts fusion-internal reads, so
`hbm_pct` can exceed 100).  This tool runs a bench entry twice in ONE
session: once plain (wall ms + cost model) and once under a device
trace, then reports the triangle

    wall ms/step      (what the user gets, incl. dispatch gaps)
    busy ms/step      (sum of device-kernel event durations / steps)
    model TFLOP/step  (XLA cost analysis)

and two MFUs: model-MFU = model_flops / wall (the bench's number) and
kernel-MFU = model_flops / busy (the achievable-if-no-gaps bound).
busy <= wall always; the gap is host dispatch + scheduling bubbles
(large on the tunnel-attached chip).  If kernel-MFU comes out near
model-MFU the model numbers are anchored; a big spread means the
metric is dispatch-bound, not compute-bound.

Usage: python tools/mfu_crosscheck.py [bert_long|bert|resnet50] [steps]
Needs the real TPU (device-kernel trace events).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PEAK_TFLOPS = 197.0  # v5e bf16


def busy_ms_per_step(logdir, steps):
    """Device kernel busy time per step: the 'XLA Ops' device lane
    ONLY — the trace nests three device lanes (Steps ⊃ XLA Modules ⊃
    XLA Ops) whose totals each cover the same wall span, so summing
    across lanes triple-counts."""
    from paddle_tpu.fluid.profiler import _load_trace_events
    events = _load_trace_events(logdir)
    pid_names = {}
    tid_names = {}
    for e in events:
        if e.get('ph') != 'M':
            continue
        if e.get('name') == 'process_name':
            pid_names[e.get('pid')] = e.get('args', {}).get('name', '')
        elif e.get('name') == 'thread_name':
            tid_names[(e.get('pid'), e.get('tid'))] = \
                e.get('args', {}).get('name', '')
    device_pids = set(p for p, n in pid_names.items()
                      if 'TPU' in n or '/device' in n.lower())
    op_lanes = set(k for k, n in tid_names.items()
                   if k[0] in device_pids and n == 'XLA Ops')
    total_us = 0.0
    for e in events:
        if e.get('ph') != 'X':
            continue
        if (e.get('pid'), e.get('tid')) not in op_lanes:
            continue
        total_us += float(e.get('dur', 0))
    return total_us / 1e3 / max(steps, 1)


def main():
    entry = sys.argv[1] if len(sys.argv) > 1 else 'bert_long'
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    import tempfile

    import bench

    fn = getattr(bench, 'bench_' + entry)
    plain = fn(steps=steps)
    wall_ms = plain.get('value') if plain.get('unit') == 'ms/step' \
        else plain.get('step_ms')
    model_tflops_rate = plain.get('tflops')
    model_tflop_step = model_tflops_rate * wall_ms / 1e3

    logdir = tempfile.mkdtemp(prefix='mfu_xchk_')
    bench.TRACE_LOGDIR = logdir
    try:
        fn(steps=steps)
    finally:
        bench.TRACE_LOGDIR = None
    busy = busy_ms_per_step(logdir, steps)

    model_mfu = plain.get('mfu_pct')
    kernel_mfu = 100.0 * model_tflop_step / (busy / 1e3) / PEAK_TFLOPS
    print('entry=%s steps=%d' % (entry, steps))
    print('wall  %.2f ms/step   (bench metric)' % wall_ms)
    print('busy  %.2f ms/step   (trace: device kernels)' % busy)
    print('gap   %.2f ms/step   (dispatch + bubbles, %.0f%% of wall)'
          % (wall_ms - busy, 100.0 * (wall_ms - busy) / wall_ms))
    print('model %.2f TFLOP/step' % model_tflop_step)
    print('MFU: model %.2f%% (vs wall)  |  kernel %.2f%% (vs busy)'
          % (model_mfu, kernel_mfu))


if __name__ == '__main__':
    main()
