"""Pallas kernel-library gate (the ops/pallas analog of
check_health.py's plane gate).

Every kernel registered in ops/pallas/common.py must honor the
auto-dispatch + dense-fallback contract:

  1. registry hygiene: a documented dense fallback per kernel, and
     the expected library members present (a kernel silently dropped
     from the package import would otherwise vanish without a gate);
  2. parity: each kernel's forced-fused (interpret) path against its
     dense reference on CPU — bitwise where the reference is exact
     (embedding gather/scatter, blockwise quantize), tolerance-bounded
     where the compiled kernel body may contract FMAs (optimizer
     updates);
  3. observability: every dispatch lands a pallas/<kernel>/dispatch_*
     counter and a last-decision record with a reason, and the
     /statusz pallas section renders them — a silent dense fallback
     cannot masquerade as a fused win in an A/B;
  4. flag hygiene: every FLAGS_pallas_* knob is declared in
     fluid/flags.py and read inside the package (tools/staticcheck.py
     enforces the same rule statically; this re-checks it live).

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import os
import sys

EXPECTED = ('flash_attention', 'fused_optimizer', 'embedding_lookup',
            'embedding_update', 'quant_collective')


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import health, monitor
    from paddle_tpu.fluid.flags import _DEFAULTS
    from paddle_tpu.ops import registry
    from paddle_tpu.ops.pallas import (common, embedding,
                                       fused_optimizer, quant_collective)

    failures = []

    # -- 1. registry hygiene -----------------------------------------
    ks = common.kernels()
    for name in EXPECTED:
        if name not in ks:
            failures.append('kernel %r not registered' % name)
        elif not ks[name].get('dense_fallback'):
            failures.append('kernel %r has no documented dense '
                            'fallback' % name)
    print('kernels registered: %s' % ', '.join(sorted(ks)))

    # -- 2. parity, forced-fused vs dense ----------------------------
    rng = np.random.RandomState(0)

    def opt_ins():
        ins = {k: [] for k in ('Param', 'Grad', 'Moment1', 'Moment2',
                               'LearningRate', 'Beta1Pow', 'Beta2Pow')}
        for i, s in enumerate([(17, 9), (70,)]):
            ins['Param'].append(jnp.asarray(
                rng.randn(*s).astype('float32')))
            ins['Grad'].append(jnp.asarray(
                rng.randn(*s).astype('float32')))
            ins['Moment1'].append(jnp.asarray(
                rng.randn(*s).astype('float32')))
            ins['Moment2'].append(jnp.asarray(
                np.abs(rng.randn(*s)).astype('float32')))
            ins['LearningRate'].append(
                jnp.asarray(np.float32(0.01 * (i + 1))))
            ins['Beta1Pow'].append(jnp.asarray(np.float32(0.9)))
            ins['Beta2Pow'].append(jnp.asarray(np.float32(0.999)))
        return ins

    for kind in ('adam', 'adamw', 'lamb'):
        ins = opt_ins()
        fluid.set_flags({'FLAGS_pallas_force': True})
        fused = fused_optimizer.apply(kind, registry.LowerCtx(0), ins,
                                      {})
        fluid.set_flags({'FLAGS_pallas_force': False})
        dense = fused_optimizer._dense(kind, registry.LowerCtx(0), ins,
                                       {})
        for slot in dense:
            for a, b in zip(fused[slot], dense[slot]):
                if not np.allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=3e-7):
                    failures.append('fused_optimizer %s %s parity'
                                    % (kind, slot))

    w = jnp.asarray(rng.randn(600, 8).astype('float32'))
    ids = jnp.asarray(np.array([3, 3, 0, 599, 3], np.int64))
    fluid.set_flags({'FLAGS_pallas_force': True})
    lf = embedding.embedding_lookup(w, ids, -1)
    gf = jax.grad(lambda w: jnp.sum(
        embedding.embedding_lookup(w, ids, -1) ** 2))(w)
    fluid.set_flags({'FLAGS_pallas_force': False})
    ld = embedding._dense_lookup(w, ids, -1)
    gd = jax.grad(lambda w: jnp.sum(
        embedding._dense_lookup(w, ids, -1) ** 2))(w)
    if not np.array_equal(np.asarray(lf), np.asarray(ld)):
        failures.append('embedding_lookup forward not bitwise')
    if not np.array_equal(np.asarray(gf), np.asarray(gd)):
        failures.append('embedding_lookup scatter-add grad not bitwise')

    mom = jnp.asarray(np.abs(rng.randn(600, 8)).astype('float32'))
    g = jnp.asarray(rng.randn(5, 8).astype('float32'))
    upd_ins = {'Param': [w], 'Moment': [mom], 'Ids': [ids],
               'Grad': [g],
               'LearningRate': [jnp.asarray(np.float32(0.1))]}
    fluid.set_flags({'FLAGS_pallas_force': True})
    uf = embedding.apply_update(registry.LowerCtx(0), upd_ins, {})
    fluid.set_flags({'FLAGS_pallas_force': False})
    ud = embedding.apply_update(registry.LowerCtx(0), upd_ins, {})
    for slot in ('ParamOut', 'MomentOut'):
        if not np.allclose(np.asarray(uf[slot][0]),
                           np.asarray(ud[slot][0]),
                           rtol=2e-6, atol=2e-6):
            failures.append('embedding_update %s parity' % slot)

    flat = jnp.asarray(rng.randn(16, 256).astype('float32'))
    qv, s = quant_collective.quantize_blocks(flat, True)

    def qref_fn(v):
        # the dense arm's q(), jitted like the arm itself runs — eager
        # evaluation rounds the scale division one ulp differently
        sr = jnp.max(jnp.abs(v), axis=-1, keepdims=True) / 127.0
        sr = jnp.where(sr > 0, sr, 1.0)
        return (jnp.clip(jnp.rint(v / sr), -127, 127).astype(jnp.int8),
                sr.astype(jnp.float32))

    qref, sref = jax.jit(qref_fn)(flat)
    if not (np.array_equal(np.asarray(qv), np.asarray(qref)) and
            np.array_equal(np.asarray(s), np.asarray(sref))):
        failures.append('quantize_blocks not bitwise vs dense q()')
    print('parity: optimizer x3, embedding lookup/grad/update, '
          'quantize_blocks ok')

    # -- 3. dispatch observability -----------------------------------
    for name in ('fused_optimizer', 'embedding_lookup',
                 'embedding_update'):
        got = monitor.counter_value(
            'pallas/%s/dispatch_fused' % name) + \
            monitor.counter_value('pallas/%s/dispatch_dense' % name)
        if not got:
            failures.append('kernel %r recorded no dispatch counter'
                            % name)
        if name not in common._LAST:
            failures.append('kernel %r recorded no last decision'
                            % name)
        elif 'reason' not in common._LAST[name]:
            failures.append('kernel %r decision lacks a reason' % name)
    rep = health.statusz().get('pallas')
    if not rep or not rep.get('kernels'):
        failures.append('/statusz pallas section missing or empty')
    else:
        for name in ('fused_optimizer', 'embedding_lookup'):
            if name not in rep['kernels']:
                failures.append('/statusz pallas section lacks %r'
                                % name)

    # -- 4. flag hygiene ---------------------------------------------
    pallas_flags = [k for k in _DEFAULTS
                    if k.startswith('FLAGS_pallas_')]
    if not pallas_flags:
        failures.append('no FLAGS_pallas_* knobs declared')
    import staticcheck
    reads = staticcheck.flag_reads(
        staticcheck._py_files(staticcheck.PKG))
    for k in pallas_flags:
        if k not in reads:
            failures.append('%s declared but never read inside '
                            'paddle_tpu/' % k)

    if failures:
        for f in failures:
            print('KERNEL GATE  ' + f)
        return 1
    print('pallas kernel library: ok (%d kernels, %d pallas flags)'
          % (len(ks), len(pallas_flags)))
    return 0


if __name__ == '__main__':
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.exit(main())
