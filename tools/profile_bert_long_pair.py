"""Capture device traces of the framework BERT-long step AND the
hand-JAX ceiling in ONE process (two trace dirs), print both kernel
rollups side by side.  The per-category diff is the map to the last
~10% framework-vs-ceiling gap (bytes and FLOPs are already at parity —
tools/diff_bert_long.py).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))



def main():
    import jax
    import diff_bert_long as D
    from profile_resnet import analyze

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    fw = D.build_framework_direct(4, 2048)
    ce = D.build_ceiling(4, 2048)
    fw(2)
    ce(2)
    for name, fn in (('framework', fw), ('ceiling', ce)):
        logdir = '/tmp/pt_prof_%s' % name
        os.system('rm -rf %s' % logdir)
        jax.profiler.start_trace(logdir)
        try:
            fn(steps)
        finally:
            jax.profiler.stop_trace()
        print('\n================ %s ================' % name)
        analyze(logdir, steps)


if __name__ == '__main__':
    main()
