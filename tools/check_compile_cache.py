"""Compile-plane regression gate: the persistent segment-executable
store must actually carry executables ACROSS PROCESSES (the runtime
analog of tests/test_compile_cache.py's in-process roundtrip).

Runs a tiny fixed-seed training program in two child processes sharing
one fresh cache directory and checks:

  process 1:  aot_compiles > 0, disk writes > 0 (populates the store)
  process 2:  compile_cache_disk_hit > 0 and segments_lowered == 0
              (every segment loads from disk; ZERO re-traces), same
              loss trajectory as process 1 bit-for-bit

A third child runs against a deliberately corrupted store and must
REPORT compile_cache_corrupt > 0 while still producing the same
losses — a bad entry recompiles, never crashes.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

STEPS = 3


def child():
    """One process: build the fixed program, run, dump counters."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1234
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[16], dtype='float32')
        h = layers.fc(x, 8, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.05).minimize(loss)
    xs = np.random.RandomState(3).randn(4, 16).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(STEPS):
            l, = exe.run(main, feed={'x': xs}, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    flat = monitor.flat()
    print('CHECK_JSON ' + json.dumps({
        'losses': losses,
        'disk_hit': flat.get('executor/compile_cache_disk_hit', 0.0),
        'disk_writes': flat.get('executor/compile_cache_disk_writes',
                                0.0),
        'aot_compiles': flat.get('executor/aot_compiles', 0.0),
        'segments_lowered': flat.get('executor/segments_lowered', 0.0),
        'corrupt': flat.get('executor/compile_cache_corrupt', 0.0),
    }))


def run_child(cache_dir):
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get('JAX_PLATFORMS', 'cpu'),
               FLAGS_compile_cache_dir=cache_dir)
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), '--child'],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for line in p.stdout.splitlines():
        if line.startswith('CHECK_JSON '):
            return json.loads(line[len('CHECK_JSON '):])
    raise RuntimeError('child produced no result (rc=%d):\n%s\n%s'
                       % (p.returncode, p.stdout[-2000:],
                          p.stderr[-2000:]))


def main():
    if '--child' in sys.argv:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        child()
        return 0
    d = tempfile.mkdtemp(prefix='ptcc_check_')
    failures = []
    try:
        p1 = run_child(d)
        p2 = run_child(d)
        print('process 1: %d aot compiles, %d disk writes'
              % (p1['aot_compiles'], p1['disk_writes']))
        print('process 2: %d disk hits, %d retraces'
              % (p2['disk_hit'], p2['segments_lowered']))
        if not p1['aot_compiles'] > 0:
            failures.append('process 1 did not AOT-compile')
        if not p1['disk_writes'] > 0:
            failures.append('process 1 wrote no cache entries')
        if not p2['disk_hit'] > 0:
            failures.append('process 2 reported no disk hits')
        if p2['segments_lowered'] != 0:
            failures.append('process 2 re-traced %d segments '
                            '(must be 0)' % p2['segments_lowered'])
        if p1['losses'] != p2['losses']:
            failures.append('trajectories diverge: %r vs %r'
                            % (p1['losses'], p2['losses']))
        # corrupt-store tolerance: truncate every entry, run again
        seg_dir = os.path.join(d, 'segments')
        for e in os.listdir(seg_dir):
            with open(os.path.join(seg_dir, e), 'r+b') as f:
                f.truncate(16)
        p3 = run_child(d)
        print('process 3 (corrupted store): %d corrupt entries '
              'tolerated' % p3['corrupt'])
        if not p3['corrupt'] > 0:
            failures.append('corrupted entries were not detected')
        if p3['losses'] != p1['losses']:
            failures.append('corrupt-store recompile diverged: %r vs '
                            '%r' % (p3['losses'], p1['losses']))
    finally:
        shutil.rmtree(d, ignore_errors=True)
    if failures:
        for f in failures:
            print('COMPILE-CACHE REGRESSION  ' + f)
        return 1
    print('compile cache: cross-process reuse OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
