"""Serving-plane gate: a live ServingExecutor under a real concurrent
soak must batch, isolate and observe correctly (the fluid.serving
analog of check_health.py's endpoint gate).

Runs one in-process sequence:

  1. two programs resident (different widths, per-tenant scopes),
     ``warmup()`` over the full power-of-two bucket ladder;
  2. a TWO-THREAD soak (mixed tenants, mixed row counts) through the
     admission queue — every per-request result must be bitwise-equal
     to direct unbatched execution of the same rows, and the
     post-warmup window must show ZERO serving-path retraces
     (``executor/segments_lowered`` / ``executor/aot_compiles`` flat,
     ``serving/retraces`` == 0);
  3. the serving monitor points (queue depth, batch occupancy,
     admission-to-completion latency, pad waste) must be populated and
     ``monitor.prometheus_text()`` must pass the fluid.health
     prom_lint;
  4. ``/healthz`` readiness must gate on serving warmup and ``/statusz``
     must list the resident programs.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import os
import sys
import threading

SOAK_REQUESTS_PER_THREAD = 24


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import health, layers, monitor, serving

    failures = []

    def build(width, seed):
        main_p, startup = fluid.Program(), fluid.Program()
        main_p.random_seed = startup.random_seed = seed
        with fluid.program_guard(main_p, startup):
            x = layers.data('x', shape=[16], dtype='float32')
            h = layers.fc(x, width, act='relu')
            y = layers.fc(h, 10, act='softmax')
        return main_p, startup, y

    exe = fluid.Executor(fluid.XLAPlace(0))
    srv = serving.ServingExecutor(max_batch=8, executor=exe)
    tenants = {}
    for name, (w, s) in (('alpha', (32, 11)), ('beta', (48, 12))):
        mp, sp, y = build(w, s)
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(sp)
        tenants[name] = (mp, sc, y)
        srv.add_program(name, mp, ['x'], [y], scope=sc)

    # -- 1. readiness gates on warmup --------------------------------
    ready, reasons = serving.readiness()
    if ready is not False or not reasons:
        failures.append('pre-warmup readiness should be (False, '
                        'reasons), got (%r, %r)' % (ready, reasons))
    if health.status()['ready']:
        failures.append('/healthz ready before serving warmup')
    srv.warmup(wait=True)
    if serving.readiness() != (True, []):
        failures.append('post-warmup readiness %r'
                        % (serving.readiness(),))
    if not health.status()['ready']:
        failures.append('/healthz not ready after serving warmup: %r'
                        % health.status()['reasons'])

    # -- 2. two-thread soak: bitwise parity, zero retraces -----------
    lowered0 = monitor.counter_value('executor/segments_lowered')
    aot0 = monitor.counter_value('executor/aot_compiles')
    results = {}
    errors = []

    def feeder(tid):
        rng = np.random.RandomState(100 + tid)
        for i in range(SOAK_REQUESTS_PER_THREAD):
            name = ('alpha', 'beta')[(tid + i) % 2]
            rows = (1, 3, 2, 7, 4)[i % 5]
            xv = rng.randn(rows, 16).astype('float32')
            try:
                out, = srv.infer(name, {'x': xv}, timeout=120)
                results[(tid, i)] = (name, xv, out)
            except Exception as e:  # noqa: BLE001
                errors.append('feeder %d req %d: %s' % (tid, i, e))

    threads = [threading.Thread(target=feeder, args=(tid,))
               for tid in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    if errors:
        failures.append('soak errors: %s' % '; '.join(errors[:3]))
    if len(results) != 2 * SOAK_REQUESTS_PER_THREAD:
        failures.append('soak served %d/%d requests'
                        % (len(results), 2 * SOAK_REQUESTS_PER_THREAD))
    lowered_soak = monitor.counter_value(
        'executor/segments_lowered') - lowered0
    aot_soak = monitor.counter_value('executor/aot_compiles') - aot0
    if lowered_soak or aot_soak:
        failures.append('serving soak retraced: %g lowered, %g aot '
                        'compiles after warmup'
                        % (lowered_soak, aot_soak))
    if monitor.counter_value('serving/retraces'):
        failures.append('serving/retraces = %g (want 0)'
                        % monitor.counter_value('serving/retraces'))
    # bitwise parity vs direct unbatched execution at the SAME bucket
    # the request ran in (coalescing picks the bucket from the total
    # batch rows, and XLA may accumulate a row's dot products in a
    # different order at a different gemm shape — so the guarantee is
    # bitwise-per-bucket, float-noise across buckets).  Every result
    # must bitwise-match one warmed bucket's unbatched run.
    ladder = (1, 2, 4, 8)
    mismatches = 0
    for (tid, i), (name, xv, out) in sorted(results.items()):
        mp, sc, y = tenants[name]
        rows = xv.shape[0]
        matched = False
        for b in [b for b in ladder if b >= rows]:
            padded, _ = serving.pad_rows_to_bucket({'x': xv}, rows, b)
            with fluid.scope_guard(sc):
                direct, = exe.run(mp, feed=padded, fetch_list=[y])
            if np.array_equal(np.asarray(direct)[:rows], out):
                matched = True
                break
        if not matched:
            mismatches += 1
    if mismatches:
        failures.append('%d/%d results differ bitwise from unbatched '
                        'execution at every ladder bucket'
                        % (mismatches, len(results)))

    # -- 3. metrics populated + lint-clean ---------------------------
    occ = monitor.histogram_value('serving/batch_occupancy')
    lat = monitor.histogram_value('serving/admit_to_done_seconds')
    if not occ or occ['count'] <= 0:
        failures.append('serving/batch_occupancy not recorded')
    if not lat or lat['count'] != 2 * SOAK_REQUESTS_PER_THREAD:
        failures.append('serving/admit_to_done_seconds count %r != %d'
                        % (lat and lat['count'],
                           2 * SOAK_REQUESTS_PER_THREAD))
    if monitor.gauge_value('serving/queue_depth/alpha', -1.0) < 0:
        failures.append('serving/queue_depth gauge missing')
    if monitor.counter_value('serving/bucket_pad_waste_bytes') <= 0:
        failures.append('serving/bucket_pad_waste_bytes not recorded '
                        '(mixed row counts must pad)')
    problems = health.prom_lint(monitor.prometheus_text())
    if problems:
        failures.append('/metrics lint: %s' % '; '.join(problems[:5]))

    # -- 4. /statusz resident-program section ------------------------
    sz = health.statusz()
    names = sorted(t['tenant'] for rep in (sz.get('serving') or [])
                   for t in rep['tenants'])
    if names != ['alpha', 'beta']:
        failures.append('/statusz serving section lists %r' % names)
    else:
        for rep in sz['serving']:
            for t in rep['tenants']:
                if not t['warmed'] or t['requests_served'] <= 0 or \
                        not t['fingerprint']:
                    failures.append('bad tenant report %r' % t)

    srv.close()
    occupancy = occ['sum'] / occ['count'] if occ and occ['count'] else 0
    print('serving soak: %d requests, %d batches, mean occupancy '
          '%.2f, %g retraces'
          % (len(results), monitor.counter_value('serving/batches'),
             occupancy, lowered_soak))
    if failures:
        for f in failures:
            print('FAIL  ' + f)
        return 1
    print('serving plane: OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
