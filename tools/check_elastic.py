"""Elastic-resilience gate: the crash-consistent checkpoint store and
cross-topology resharding must survive REAL process boundaries (the
fluid/elastic.py analog of check_compile_cache.py's posture).

Note on topology: cross-process jax collectives are unavailable on
this container's CPU backend (the known env-level limitation the
tier-1 suite documents), so "rank" here is the suite's standard
cluster-in-a-box posture — devices of a virtual host platform — while
every save/restart boundary is a REAL OS process boundary, which is
what the store and the compile cache actually gate.

Phases, one shared store + compile-cache dir:

  1. a child process trains a dp2 layout over 2 host devices
     (CompiledProgram runner) with FLAGS_elastic_checkpoint=1 and
     saves MID-RUN through fluid.io.save_persistables, then keeps
     training (the continuation trajectory is the parity reference);
  2. a fresh process restarts as ONE device: load_persistables
     auto-detects the store, resumes on the same global batches at
     loss parity, and Executor.warmup() + the persistent compile
     cache give ZERO post-warmup retraces;
  3. a fresh process restarts as a DIFFERENT layout (fsdp2 via the
     auto-shard planner): parity again, elastic/reshard_* populated;
  4. chaos: a child killed MID-SAVE (faultinject
     'elastic.shard_write:die') must leave the previous generation
     loadable; a child writing a TORN shard must publish a generation
     the loader refuses BY NAME before falling back to last-good.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRE_STEPS = 3     # steps before the checkpoint
POST_STEPS = 3    # steps after it (the compared trajectory)


def build_model():
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, 16, act='relu')
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def make_batches(steps=PRE_STEPS + POST_STEPS, n=8):
    import numpy as np
    rng = np.random.RandomState(5)
    out = []
    for _ in range(steps):
        x = rng.randn(n, 8).astype('float32')
        y = x.sum(1, keepdims=True).astype('float32') * 0.5
        out.append((x, y))
    return out


def _f(v):
    import numpy as np
    return float(np.asarray(v).ravel()[0])


def _param_sample(main):
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.parallel_executor import _fetch_to_host
    pname = main.all_parameters()[0].name
    return np.asarray(_fetch_to_host(
        fluid.global_scope().find_var(pname))).tolist()


def _compiled(main, loss, ndev, layout=None):
    import paddle_tpu.fluid as fluid
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name,
        places=[fluid.XLAPlace(i) for i in range(ndev)])
    if layout is not None:
        from paddle_tpu.parallel import plan as ashard
        comp._auto_plan = ashard.build_plan(main, ndev=ndev,
                                            layouts=[layout])
    return comp


def child_main(mode, ckpt_dir):
    """One process of the gate.  Modes: 'save2' (dp2 trainer that
    saves mid-run), 'single' (1-device resume through warmup),
    'fsdp2' (different-layout resume), 'chaos-save' (one more
    generation under the parent's FLAGS_faultinject)."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import elastic, monitor
    main, startup, loss = build_model()
    batches = make_batches()
    out = {}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        if mode == 'save2':
            target = _compiled(main, loss, 2)
            exe.run(startup)
            losses = []
            for i, (x, y) in enumerate(batches):
                l, = exe.run(target, feed={'x': x, 'y': y},
                             fetch_list=[loss])
                losses.append(_f(l))
                if i + 1 == PRE_STEPS:
                    fluid.io.save_persistables(exe, ckpt_dir, main)
            out = {'losses': losses,
                   'saved': monitor.counter_value(
                       'elastic/checkpoints_saved'),
                   'save_bytes': monitor.counter_value(
                       'elastic/save_bytes')}
        elif mode == 'chaos-save':
            fluid.io.load_persistables(exe, ckpt_dir, main)
            x, y = batches[0]
            exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
            elastic.save_checkpoint(ckpt_dir, main, executor=exe)
            print('SAVE_DONE')
            return
        else:
            if mode == 'fsdp2':
                fluid.set_flags({'FLAGS_auto_shard': True})
                target = _compiled(main, loss, 2, layout=(1, 2, 1))
                fluid.io.load_persistables(exe, ckpt_dir, main)
                lowered_after_warmup = None
            else:       # 'single': 1 device, warmup, zero retraces
                target = main
                fluid.io.load_persistables(exe, ckpt_dir, main)
                x0, y0 = batches[PRE_STEPS]
                exe.warmup(main, feed_shapes={'x': x0, 'y': y0},
                           fetch_list=[loss], wait=True)
                lowered_after_warmup = monitor.counter_value(
                    'executor/segments_lowered')
            losses = []
            for x, y in batches[PRE_STEPS:]:
                l, = exe.run(target, feed={'x': x, 'y': y},
                             fetch_list=[loss])
                losses.append(_f(l))
            rep = elastic.report()
            out = {
                'losses': losses,
                'loaded_generation':
                    (rep['last_load'] or {}).get('generation'),
                'reshard_by_kind':
                    ((rep['last_load'] or {}).get('reshard')
                     or {}).get('by_kind'),
                'reshard_params': monitor.counter_value(
                    'elastic/reshard_params'),
                'staging_waves': monitor.counter_value(
                    'elastic/staging_waves'),
                'refused': monitor.counter_value(
                    'elastic/refused_generations'),
                'refusal_shard': (rep['refusals'][-1]['shard']
                                  if rep['refusals'] else None),
                'lowered_after_warmup': lowered_after_warmup,
                'lowered_total': monitor.counter_value(
                    'executor/segments_lowered'),
                'disk_hit': monitor.counter_value(
                    'executor/compile_cache_disk_hit'),
            }
        out['param'] = _param_sample(main)
    print('CHECK_JSON ' + json.dumps(out))


# ------------------------------------------------------------- driver
def _spawn(mode, ckpt, extra_env=None, timeout=540):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), '--child', mode,
         ckpt],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout)


def _child_json(p):
    for line in p.stdout.splitlines():
        if line.startswith('CHECK_JSON '):
            return json.loads(line[len('CHECK_JSON '):])
    raise RuntimeError('child produced no CHECK_JSON (rc=%d)\n%s\n%s'
                       % (p.returncode, p.stdout[-2000:],
                          p.stderr[-2000:]))


def main():
    if '--child' in sys.argv:
        i = sys.argv.index('--child')
        sys.path.insert(0, REPO)
        return child_main(sys.argv[i + 1], sys.argv[i + 2])

    import numpy as np
    work = tempfile.mkdtemp(prefix='pt_elastic_check_')
    ckpt = os.path.join(work, 'store')
    cache = os.path.join(work, 'cache')
    dev2 = {'XLA_FLAGS': '--xla_force_host_platform_device_count=2'}
    failures = []
    try:
        # ---- phase 1: dp2 trainer saves mid-run, keeps training
        p1 = _child_json(_spawn(
            'save2', ckpt, dict(dev2, FLAGS_elastic_checkpoint='1',
                                FLAGS_compile_cache_dir=cache)))
        print('phase 1: dp2 trainer saved %d bytes mid-run, trained '
              '%d steps' % (p1['save_bytes'], len(p1['losses'])))
        if p1['saved'] != 1:
            failures.append('saver wrote %r generations, wanted 1'
                            % p1['saved'])
        ref_losses = p1['losses'][PRE_STEPS:]
        ref_param = np.asarray(p1['param'])

        # ---- phase 2: restart as ONE device, warmup, zero retraces
        p2 = _child_json(_spawn(
            'single', ckpt, {'FLAGS_compile_cache_dir': cache}))
        print('phase 2: single-device resume, gen %s, %d reshard '
              'params, %d segments lowered post-warmup'
              % (p2['loaded_generation'], p2['reshard_params'],
                 p2['lowered_total'] - p2['lowered_after_warmup']))
        if p2['loaded_generation'] != 1:
            failures.append('restart loaded generation %r, wanted 1'
                            % p2['loaded_generation'])
        if not np.allclose(p2['losses'], ref_losses, rtol=1e-4,
                           atol=1e-6):
            failures.append('single-device resume diverged: %r vs %r'
                            % (p2['losses'], ref_losses))
        if not np.allclose(p2['param'], ref_param, rtol=1e-4,
                           atol=1e-6):
            failures.append('single-device resumed params diverged')
        if p2['lowered_total'] != p2['lowered_after_warmup']:
            failures.append('%d segments re-traced AFTER warmup '
                            '(must be 0)'
                            % (p2['lowered_total'] -
                               p2['lowered_after_warmup']))
        if p2['reshard_params'] <= 0:
            failures.append('restart reported no resharded params')

        # ---- phase 3: restart as a DIFFERENT layout (fsdp2)
        p3 = _child_json(_spawn(
            'fsdp2', ckpt, dict(dev2, FLAGS_compile_cache_dir=cache)))
        print('phase 3: fsdp2-layout resume, gen %s, schedule %s, '
              'staging waves %d'
              % (p3['loaded_generation'], p3['reshard_by_kind'],
                 p3['staging_waves']))
        if p3['loaded_generation'] != 1:
            failures.append('fsdp2 restart loaded generation %r'
                            % p3['loaded_generation'])
        if not np.allclose(p3['losses'], ref_losses, rtol=1e-4,
                           atol=1e-6):
            failures.append('fsdp2 resume diverged: %r vs %r'
                            % (p3['losses'], ref_losses))
        if not np.allclose(p3['param'], ref_param, rtol=1e-4,
                           atol=1e-6):
            failures.append('fsdp2 resumed params diverged')
        if not p3['reshard_by_kind']:
            failures.append('fsdp2 restart recorded no reshard '
                            'schedule')
        if p3['staging_waves'] <= 0:
            failures.append('fsdp2 restart recorded no staging waves')

        # ---- phase 4a: kill -9 mid-save never corrupts last-good
        gens_before = sorted(e for e in os.listdir(ckpt)
                             if e.startswith('gen-'))
        pk = _spawn('chaos-save', ckpt,
                    {'FLAGS_faultinject': 'elastic.shard_write:die@2'})
        if pk.returncode != 9:
            failures.append('mid-save kill child exited %d, wanted 9'
                            % pk.returncode)
        gens_after = sorted(e for e in os.listdir(ckpt)
                            if e.startswith('gen-'))
        if gens_before != gens_after:
            failures.append('killed save published a generation: %r '
                            '-> %r' % (gens_before, gens_after))
        pv = _child_json(_spawn('single', ckpt))
        if pv['loaded_generation'] != 1:
            failures.append('store unloadable after mid-save kill '
                            '(gen %r)' % pv['loaded_generation'])
        print('phase 4a: mid-save kill left generation 1 loadable')

        # ---- phase 4b: a torn PUBLISHED shard is refused by name
        pt = _spawn('chaos-save', ckpt,
                    {'FLAGS_faultinject': 'elastic.shard_write:torn@2'})
        if pt.returncode != 0 or 'SAVE_DONE' not in pt.stdout:
            failures.append('torn-write child failed rc=%d\n%s'
                            % (pt.returncode, pt.stderr[-1000:]))
        pr = _child_json(_spawn('single', ckpt))
        if pr['refused'] != 1:
            failures.append('torn generation was not refused '
                            '(refused=%r)' % pr['refused'])
        if not pr['refusal_shard'] or \
                not str(pr['refusal_shard']).endswith('.npy'):
            failures.append('refusal did not name the torn shard '
                            '(%r)' % pr['refusal_shard'])
        if pr['loaded_generation'] != 1:
            failures.append('loader did not fall back to last-good '
                            '(gen %r)' % pr['loaded_generation'])
        print('phase 4b: torn shard %s refused by name, last-good '
              'loaded' % pr['refusal_shard'])
    finally:
        shutil.rmtree(work, ignore_errors=True)

    if failures:
        print('\ncheck_elastic FAILURES:')
        for f in failures:
            print('  - ' + f)
        return 1
    print('\ncheck_elastic OK: crash-consistent store survives '
          'kill -9, torn shards refused by name, dp2 -> single and '
          'dp2 -> fsdp2 resumes at parity with zero post-warmup '
          'retraces')
    return 0


if __name__ == '__main__':
    sys.exit(main())
