"""Static Program verifier, CLI mode.

Usage:
  python tools/progcheck.py model.py [model2.py ...]
  python tools/progcheck.py --strict model.py    # warnings fail too
  python tools/progcheck.py --json model.py      # machine-readable

Executes each python file (with ``__name__`` set to ``'__progcheck__'``
so ``if __name__ == '__main__':`` training loops stay dormant — build
your programs at module level or behind that guard), then runs the
fluid.progcheck static pass over EVERY Program the file built
(framework.all_live_programs): graph invariants, the shape/dtype
inference walk, donation hazards, fingerprint-stability lint.

Exit status: 0 = every program verifies clean of errors (warnings
reported), 1 = at least one error-class diagnostic (or, with
--strict, any diagnostic), 2 = usage / file error.

The CI-shaped entry: a graph-rewriting change can prove its output
legal before anything traces, without standing up an executor.
"""

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_file(path):
    """Exec one model file; returns the Programs it built."""
    from paddle_tpu.fluid import framework
    before = set(id(p) for p in framework.all_live_programs())
    glb = {'__name__': '__progcheck__',
           '__file__': os.path.abspath(path)}
    with open(path) as f:
        src = f.read()
    code = compile(src, path, 'exec')
    exec(code, glb)
    # keep the exec globals alive until after the snapshot — programs
    # referenced only by the file's module scope must not be collected
    programs = [p for p in framework.all_live_programs()
                if id(p) not in before and
                any(b.ops for b in p.blocks)]
    glb['__progcheck_hold__'] = True
    return programs, glb


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    strict = '--strict' in argv
    as_json = '--json' in argv
    files = [a for a in argv if not a.startswith('--')]
    if not files:
        sys.stderr.write(__doc__)
        return 2
    sys.path.insert(0, ROOT)
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    from paddle_tpu.fluid import progcheck
    failed = False
    out_docs = []
    for path in files:
        if not os.path.exists(path):
            sys.stderr.write('progcheck: no such file: %s\n' % path)
            return 2
        try:
            programs, hold = run_file(path)
        except Exception as e:
            sys.stderr.write('progcheck: %s failed to execute: %s: %s\n'
                             % (path, type(e).__name__, e))
            return 2
        if not programs:
            print('%s: no Programs built (build them at module level)'
                  % path)
        for idx, prog in enumerate(programs):
            label = '%s#%d' % (os.path.basename(path), idx)
            rep = progcheck.verify_program(
                prog, label=label, origin='cli', level='full',
                raise_on_error=False)
            bad = rep.errors or (strict and rep.warnings)
            failed = failed or bool(bad)
            if as_json:
                out_docs.append(rep.to_dict())
            else:
                print(rep.format())
        del hold
    if as_json:
        print(json.dumps(out_docs, indent=2, sort_keys=True))
    return 1 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
