"""Run-to-run perf regression gate: compare the current bench run
against the recorded baseline envelope in BENCH_history.jsonl, with a
NAMED per-series verdict — a perf regression fails CI the way a torn
checkpoint already does.

Noise-aware by construction: the baseline is the BAND (min..max) of
the recorded runs widened by --tolerance around the baseline median,
and the current value is the MEDIAN of the newest --current-n runs —
median-of-N vs band, never single-sample vs single-sample.  Only
metrics with a known direction are gated (time-like: lower is better;
throughput-like: higher is better); everything else is reported INFO.

Postures:

  check_regress.py                      gate ./BENCH_history.jsonl
  check_regress.py --history F --entry E --current-n 3
  check_regress.py --selftest           hermetic proof (make check):
      a real executor micro-bench records 3 baseline runs into a temp
      history, an honest 4th run must PASS, and a 5th run under a
      seeded FLAGS_faultinject executor.step delay clause must FAIL
      with the slowed series named.

Exit 1 on any REGRESS verdict (or a failed selftest).  Run from
`make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# direction by series name: gate only what we can call honestly
_HIGHER_RE = re.compile(
    r'(per_sec|per_second|throughput|tflops|mfu|gbps|speedup)',
    re.IGNORECASE)
_LOWER_RE = re.compile(
    r'(seconds|step_s$|_ms$|_us$|wall_|p50|p95|p99|latency)',
    re.IGNORECASE)
# steady-state gates only: cache-state-dependent series regress for
# environmental reasons (a cold cache dir) and would cry wolf
_SKIP_RE = re.compile(
    r'(compile|cold|warmup|vs_baseline|cache|bytes|calls$|count$'
    r'|hits$|lookups$|ts$)', re.IGNORECASE)


def direction(metric):
    """'higher' / 'lower' / None (INFO-only series)."""
    if _SKIP_RE.search(metric):
        return None
    if _HIGHER_RE.search(metric):
        return 'higher'
    if _LOWER_RE.search(metric):
        return 'lower'
    return None


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    if not n:
        return None
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def load_history(path):
    """BENCH_history.jsonl -> [line dicts], oldest first (append
    order IS time order; a torn tail line is skipped, not fatal)."""
    lines = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get('entry') and \
                    isinstance(rec.get('metrics'), dict):
                lines.append(rec)
    return lines


def compare(lines, entry=None, current_n=1, tolerance=0.30,
            min_baseline=2, rel_floor=1e-9):
    """The comparer: split each entry's lines into baseline (all but
    the newest `current_n`) and current (the newest `current_n`),
    then verdict per metric.  Returns a list of
    {entry, metric, status, current, band, baseline_n, direction}
    with status REGRESS / PASS / INFO."""
    by_entry = {}
    for rec in lines:
        if entry and rec['entry'] != entry:
            continue
        by_entry.setdefault(rec['entry'], []).append(rec)
    verdicts = []
    for ent in sorted(by_entry):
        recs = by_entry[ent]
        if len(recs) <= current_n:
            verdicts.append({'entry': ent, 'metric': '*',
                             'status': 'INFO', 'current': None,
                             'band': None, 'baseline_n': len(recs),
                             'direction': None,
                             'note': 'only %d run(s) recorded: '
                                     'nothing to gate against'
                                     % len(recs)})
            continue
        base, cur = recs[:-current_n], recs[-current_n:]
        metrics = sorted(set(
            m for r in cur for m in r['metrics']))
        for m in metrics:
            base_vals = [r['metrics'][m] for r in base
                         if m in r['metrics']]
            cur_vals = [r['metrics'][m] for r in cur
                        if m in r['metrics']]
            cur_v = _median(cur_vals)
            d = direction(m)
            v = {'entry': ent, 'metric': m, 'current': cur_v,
                 'direction': d, 'baseline_n': len(base_vals)}
            if d is None:
                v.update(status='INFO', band=None)
            elif len(base_vals) < min_baseline:
                v.update(status='INFO', band=None,
                         note='baseline too thin (%d < %d runs)'
                              % (len(base_vals), min_baseline))
            else:
                med = _median(base_vals)
                pad = max(tolerance * abs(med), rel_floor)
                lo = min(base_vals) - pad
                hi = max(base_vals) + pad
                v['band'] = [lo, hi]
                bad = (cur_v > hi) if d == 'lower' else (cur_v < lo)
                v['status'] = 'REGRESS' if bad else 'PASS'
            verdicts.append(v)
    return verdicts


def render(verdicts, show_info=False):
    worst = 0
    for v in verdicts:
        if v['status'] == 'INFO' and not show_info:
            continue
        if v['status'] == 'REGRESS':
            worst = 1
            arrow = 'above' if v['direction'] == 'lower' else 'below'
            print('REGRESS  %s %s: current %.6g %s baseline band '
                  '[%.6g, %.6g] over %d run(s)'
                  % (v['entry'], v['metric'], v['current'], arrow,
                     v['band'][0], v['band'][1], v['baseline_n']))
        elif v['status'] == 'PASS':
            print('PASS     %s %s: current %.6g within [%.6g, %.6g]'
                  % (v['entry'], v['metric'], v['current'],
                     v['band'][0], v['band'][1]))
        else:
            print('INFO     %s %s: %s'
                  % (v['entry'], v['metric'],
                     v.get('note', 'no gated direction')))
    return worst


# ------------------------------------------------------------ selftest
def _measure_run(exe, prog, feed, loss, steps):
    import time
    t0 = time.perf_counter()
    for _ in range(steps):
        exe.run(prog, feed=feed, fetch_list=[loss])
    return (time.perf_counter() - t0) / steps


def selftest():
    """The make-check proof: the comparer must pass an honest rerun
    of a REAL micro-bench and fail, by name, a rerun slowed by a
    seeded faultinject delay clause."""
    import tempfile
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, ROOT)
    hist = os.path.join(tempfile.mkdtemp(prefix='pt_regress_'),
                        'BENCH_history.jsonl')
    import bench as bench_mod
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import faultinject, layers
    import numpy as np

    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 7
    with fluid.program_guard(prog, startup):
        x = layers.data('x', shape=[32], dtype='float32')
        h = layers.fc(x, 32, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    feed = {'x': np.ones((8, 32), 'float32')}
    steps, runs = 30, 3
    failures = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(8):          # warm caches out of the window
            exe.run(prog, feed=feed, fetch_list=[loss])
        for _r in range(runs):      # the recorded baseline
            step_s = _measure_run(exe, prog, feed, loss, steps)
            bench_mod.append_history(
                'regress_selftest', {'step_s': step_s}, path=hist)
        # honest rerun: same posture, must sit inside the band
        step_s = _measure_run(exe, prog, feed, loss, steps)
        bench_mod.append_history('regress_selftest',
                                 {'step_s': step_s}, path=hist)
        honest = compare(load_history(hist))
        if any(v['status'] == 'REGRESS' for v in honest):
            failures.append('honest rerun flagged as regression: %r'
                            % [v for v in honest
                               if v['status'] == 'REGRESS'])
        if not any(v['status'] == 'PASS' and v['metric'] == 'step_s'
                   for v in honest):
            failures.append('honest rerun produced no PASS verdict '
                            'for step_s: %r' % honest)
        # seeded slowdown: a per-step faultinject delay clause an
        # order of magnitude above the honest step wall
        delay = max(10 * step_s, 0.005)
        fluid.set_flags({'FLAGS_faultinject':
                         'executor.step:delay:%g@1+' % delay})
        faultinject.configure()
        try:
            slow_s = _measure_run(exe, prog, feed, loss, steps)
        finally:
            fluid.set_flags({'FLAGS_faultinject': ''})
            faultinject.configure()
        bench_mod.append_history('regress_selftest',
                                 {'step_s': slow_s}, path=hist)
        seeded = compare(load_history(hist))
        named = [v for v in seeded if v['status'] == 'REGRESS'
                 and v['entry'] == 'regress_selftest'
                 and v['metric'] == 'step_s']
        if not named:
            failures.append(
                'seeded %.0fms/step delay not flagged: honest %.5fs '
                'vs slowed %.5fs, verdicts %r'
                % (1e3 * delay, step_s, slow_s, seeded))
    print('regress selftest: honest %.5fs/step in band, seeded '
          '+%.0fms delay -> %.5fs/step'
          % (step_s, 1e3 * delay, slow_s))
    if failures:
        for f in failures:
            print('REGRESS-GATE BROKEN  ' + f)
        return 1
    print('regress selftest: honest rerun PASSed, seeded slowdown '
          'REGRESSed by name')
    return 0


def main(argv):
    args = list(argv)
    if '--selftest' in args:
        return selftest()
    history = os.path.join(ROOT, 'BENCH_history.jsonl')
    entry, current_n, tolerance = None, 1, 0.30
    show_info = '--verbose' in args
    i = 0
    while i < len(args):
        a = args[i]
        if a == '--history':
            i += 1
            history = args[i]
        elif a == '--entry':
            i += 1
            entry = args[i]
        elif a == '--current-n':
            i += 1
            current_n = int(args[i])
        elif a == '--tolerance':
            i += 1
            tolerance = float(args[i])
        i += 1
    if not os.path.exists(history):
        print('check_regress: no history at %s (run bench.py first); '
              'nothing to gate' % history)
        return 0
    verdicts = compare(load_history(history), entry=entry,
                       current_n=current_n, tolerance=tolerance)
    rc = render(verdicts, show_info=show_info)
    gated = sum(1 for v in verdicts if v['status'] in ('PASS',
                                                       'REGRESS'))
    print('check_regress: %d series gated, %d regressed'
          % (gated, sum(1 for v in verdicts
                        if v['status'] == 'REGRESS')))
    return rc


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
