"""Stat-coverage audit: the monitor instrument points the observability
contract depends on must stay in the source (the CI-gate analog of
check_op_coverage.py, for fluid.monitor instead of the op registry).

Each entry below is (file, literal stat key) — a refactor that drops
one silently blinds production scraping, so this exits nonzero and
names the missing point.  Run from `make check`.
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (repo-relative file, substring that must appear in it)
REQUIRED = [
    # executor core: segment executable cache, compile latency, volume
    ('paddle_tpu/fluid/executor.py', 'executor/segment_cache_hit'),
    ('paddle_tpu/fluid/executor.py', 'executor/segment_cache_miss'),
    ('paddle_tpu/fluid/executor.py', 'executor/segments_lowered'),
    ('paddle_tpu/fluid/executor.py', 'executor/segment_compile_seconds'),
    ('paddle_tpu/fluid/executor.py', 'executor/plan_cache_hit'),
    ('paddle_tpu/fluid/executor.py', 'executor/feed_bytes'),
    ('paddle_tpu/fluid/executor.py', 'executor/fetch_bytes'),
    ('paddle_tpu/fluid/executor.py', 'executor/run_seconds'),
    ('paddle_tpu/fluid/executor.py', 'executor/host_ops_run'),
    # steady-state fast path (PR 2): binder cache behavior, batched
    # async H2D, blocked fetch time — tools/check_hot_path.py budgets
    # these per step
    ('paddle_tpu/fluid/executor.py', 'executor/fastpath_hits'),
    ('paddle_tpu/fluid/executor.py', 'executor/scope_lookups'),
    ('paddle_tpu/fluid/executor.py', 'executor/bind_seconds'),
    ('paddle_tpu/fluid/executor.py', 'executor/h2d_bytes_async'),
    ('paddle_tpu/fluid/executor.py', 'executor/fetch_blocked_seconds'),
    ('paddle_tpu/fluid/executor.py', 'executor/plan_cache_bypass'),
    # AOT compile plane (PR 3): content-addressed executable reuse
    # across processes, background warmup, bounded in-memory caches —
    # tools/check_compile_cache.py exercises the cross-process path
    ('paddle_tpu/fluid/compile_cache.py',
     'executor/compile_cache_disk_hit'),
    ('paddle_tpu/fluid/compile_cache.py',
     'executor/compile_cache_disk_miss'),
    ('paddle_tpu/fluid/compile_cache.py',
     'executor/compile_cache_memory_hit'),
    ('paddle_tpu/fluid/compile_cache.py',
     'executor/compile_cache_corrupt'),
    ('paddle_tpu/fluid/executor.py', 'executor/aot_compiles'),
    ('paddle_tpu/fluid/executor.py', 'executor/warmup_seconds'),
    ('paddle_tpu/fluid/executor.py', 'executor/warmup_segments'),
    ('paddle_tpu/fluid/executor.py',
     'executor/segment_cache_evictions'),
    ('paddle_tpu/fluid/framework.py',
     'executor/plan_cache_evictions'),
    ('paddle_tpu/fluid/executor.py',
     'executor/compile_cache_fallbacks'),
    # data-parallel / collective runners
    ('paddle_tpu/fluid/parallel_executor.py', 'parallel/device_count'),
    ('paddle_tpu/fluid/parallel_executor.py',
     'parallel/segment_cache_miss'),
    ('paddle_tpu/fluid/parallel_executor.py',
     'parallel/segment_compile_seconds'),
    ('paddle_tpu/fluid/compiler.py',
     'compiler/data_parallel_programs_built'),
    # async input pipeline
    ('paddle_tpu/fluid/reader.py', 'reader/queue_depth'),
    ('paddle_tpu/fluid/reader.py', 'reader/batches_produced'),
    ('paddle_tpu/fluid/reader.py', 'reader/batches_consumed'),
    ('paddle_tpu/fluid/reader.py', 'reader/consume_blocked_seconds'),
    ('paddle_tpu/fluid/reader.py', 'reader/bytes_staged'),
    # PS / RPC planes
    ('paddle_tpu/fluid/incubate/fleet/parameter_server/__init__.py',
     'ps/push_bytes'),
    ('paddle_tpu/fluid/incubate/fleet/parameter_server/__init__.py',
     'ps/step_seconds'),
    ('paddle_tpu/distributed/rpc_ps.py', 'rpc/calls'),
    ('paddle_tpu/distributed/rpc_ps.py', 'rpc/call_seconds'),
    ('paddle_tpu/distributed/rpc_ps.py', 'rpc/retries'),
    ('paddle_tpu/distributed/communicator.py', 'communicator/sends'),
    ('paddle_tpu/distributed/communicator.py',
     'communicator/grads_merged'),
    # collective rewrites + trace-time lowering accounting
    ('paddle_tpu/fluid/transpiler/collective.py',
     'collective/%s_ops_inserted'),
    ('paddle_tpu/ops/collective_ops.py', 'collective/traced_bytes'),
    # profiler fold-in + bench export
    ('paddle_tpu/fluid/profiler.py', "profiler/%s/calls"),
    ('bench.py', '_monitor_fields'),
    # span tracer / flight recorder (fluid/trace.py): its own counters
    # keep the trace plane observable through the monitor plane, and
    # the phase-span instrument sites across the hot path feed the
    # step_report() contract tools/check_trace.py gates end to end
    ('paddle_tpu/fluid/trace.py', 'trace/spans_recorded'),
    ('paddle_tpu/fluid/trace.py', 'trace/steps_recorded'),
    ('paddle_tpu/fluid/trace.py', 'trace/steps_dropped'),
    ('paddle_tpu/fluid/trace.py', 'trace/dumps_written'),
    ('paddle_tpu/fluid/executor.py', "_trace.span('feed_h2d'"),
    ('paddle_tpu/fluid/executor.py', "_trace.record('bind'"),
    ('paddle_tpu/fluid/executor.py', "else 'dispatch'"),
    ('paddle_tpu/fluid/executor.py', "_trace.record('fetch_d2h'"),
    ('paddle_tpu/fluid/executor.py', 'executor/state_release_seconds'),
    ('paddle_tpu/fluid/reader.py', "_trace.record('reader_wait'"),
    ('paddle_tpu/fluid/parallel_executor.py', "_trace.step_span"),
    ('paddle_tpu/fluid/compile_cache.py', "'cache_deserialize'"),
    ('bench.py', '_step_phase_fields'),
    # health plane (fluid/health.py): the HTTP status surface, the
    # aggregator's worker probes, the tensor-health summaries and the
    # NaN/divergence detectors — tools/check_health.py exercises the
    # endpoints end to end, this audit keeps the instrument points
    ('paddle_tpu/fluid/health.py', 'health/http_requests'),
    ('paddle_tpu/fluid/health.py', 'health/scrapes'),
    ('paddle_tpu/fluid/health.py', 'health/worker_up'),
    ('paddle_tpu/fluid/health.py', 'health/summary_steps'),
    ('paddle_tpu/fluid/health.py', 'health/global_grad_norm'),
    ('paddle_tpu/fluid/health.py', 'health/update_ratio'),
    ('paddle_tpu/fluid/health.py', 'health/grad_spikes'),
    ('paddle_tpu/fluid/health.py', 'health/zero_update_trips'),
    ('paddle_tpu/fluid/health.py', 'health/detector_dumps'),
    ('paddle_tpu/fluid/executor.py', 'health/nan_trips'),
    ('paddle_tpu/fluid/executor.py', 'executor/last_step_unix_ts'),
    ('paddle_tpu/fluid/monitor.py', '# HELP'),
    ('paddle_tpu/distributed/launch.py', 'PADDLE_TPU_STATUS_WORKERS'),
    ('bench.py', 'health_overhead'),
    # serving plane (fluid/serving.py): continuous-batching SLO
    # surface — per-tenant queue depth, batch occupancy,
    # admission-to-completion latency, pad waste, and the
    # zero-retrace-after-warmup accounting; tools/check_serving.py
    # exercises them against a live two-thread soak
    ('paddle_tpu/fluid/serving.py', 'serving/queue_depth'),
    ('paddle_tpu/fluid/serving.py', 'serving/batch_occupancy'),
    ('paddle_tpu/fluid/serving.py', 'serving/admit_to_done_seconds'),
    ('paddle_tpu/fluid/serving.py', 'serving/bucket_pad_waste_bytes'),
    ('paddle_tpu/fluid/serving.py', 'serving/requests'),
    ('paddle_tpu/fluid/serving.py', 'serving/batches'),
    ('paddle_tpu/fluid/serving.py', 'serving/retraces'),
    ('paddle_tpu/fluid/serving.py', 'serving/warmup_buckets'),
    ('paddle_tpu/fluid/serving.py', "_trace.step_tags"),
    ('paddle_tpu/fluid/trace.py', 'step_tags'),
    ('bench.py', 'serving_requests_per_sec'),
    # job-wide observability (fluid/comms.py + trace.collect_job +
    # the aggregator's skew detector): collective telemetry with
    # bytes-on-wire and per-(collective, size-bucket) bandwidth,
    # cross-worker trace collection tolerance counters, per-segment
    # XLA memory gauges, and the straggler detector —
    # tools/check_comms.py exercises the whole plane against a real
    # two-process job
    ('paddle_tpu/fluid/comms.py', 'comms/bytes_on_wire'),
    ('paddle_tpu/fluid/comms.py', 'comms/payload_bytes'),
    ('paddle_tpu/fluid/comms.py', 'comms/collective_calls'),
    ('paddle_tpu/fluid/comms.py', 'comms/bw_gbps'),
    ('paddle_tpu/fluid/comms.py', 'executor/segment_peak_bytes'),
    ('paddle_tpu/fluid/comms.py', 'executor/segment_temp_bytes'),
    ('paddle_tpu/ops/collective_ops.py', 'comms.record_trace'),
    ('paddle_tpu/ops/parallel_ops.py', 'comms.record_trace'),
    ('paddle_tpu/fluid/parallel_executor.py',
     'comms.account_dispatch'),
    ('paddle_tpu/fluid/parallel_executor.py', 'comms.collecting'),
    # collective planner (fluid/comms_plan.py + the planned lowerings
    # in ops/collective_ops.py + the GradAllReduce bucket rewrite):
    # which arm ran, actual vs dense-equivalent wire bytes, the cost
    # model's predicted-vs-measured honesty, and the planner digest
    # folded into both runner fingerprints — tools/check_comms.py
    # asserts the counters move on a real quantized two-process job
    ('paddle_tpu/fluid/comms.py', 'comms/plan_arm/'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_wire_bytes'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_dense_equiv_bytes'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_predicted_seconds'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_measured_seconds'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_pred_over_measured'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_unpriced'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_fused_grads'),
    ('paddle_tpu/fluid/transpiler/collective.py',
     'collective/plan_buckets'),
    ('paddle_tpu/fluid/transpiler/collective.py',
     'collective/plan_fused_grads'),
    ('paddle_tpu/ops/collective_ops.py', '_planned_allreduce'),
    ('paddle_tpu/fluid/parallel_executor.py', 'comms_plan.digest'),
    ('paddle_tpu/fluid/health.py', 'comms_plan.program_plans'),
    ('bench.py', '_plan_ab_fields'),
    ('paddle_tpu/fluid/executor.py', '_comms.record_memory'),
    # a restarted (disk-hit) process must keep memory accounting
    ('paddle_tpu/fluid/compile_cache.py', 'comms.record_memory'),
    ('paddle_tpu/fluid/trace.py', 'trace/collect_skipped'),
    ('paddle_tpu/fluid/trace.py', 'trace/collect_unanchored'),
    ('paddle_tpu/fluid/trace.py', 'ptClock'),
    ('paddle_tpu/fluid/health.py', 'comms/skew_ratio'),
    ('paddle_tpu/fluid/health.py', 'comms/straggler_trips'),
    ('paddle_tpu/fluid/health.py', 'step_rollup'),
    ('paddle_tpu/distributed/launch.py', 'PADDLE_TPU_STATUS_WORKERS'),
    ('tools/comms_calibrate.py', 'inv_bw_s_per_byte'),
    ('tools/timeline.py', 'collect_job'),
    ('bench.py', 'bytes_on_wire'),
    # device-memory observability plane (fluid/memviz.py): per-
    # (program, segment) peak attribution, the live-HBM census sampler
    # + Perfetto counter track, OOM forensics and budget watermarks —
    # tools/check_memviz.py exercises the plane against a warmed LeNet
    ('paddle_tpu/fluid/memviz.py', 'memviz/segments_attributed'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/program_peak_bytes'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/live_bytes/'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/live_bytes_total'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/live_bytes_hwm'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/budget_utilization'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/watermark_trips'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/spike_trips'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/oom_incidents'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/oom_dumps'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/analysis_unavailable'),
    ('paddle_tpu/fluid/memviz.py', 'memviz/samples'),
    ('paddle_tpu/fluid/executor.py', '_memviz.record_segment'),
    ('paddle_tpu/fluid/executor.py', '_memviz.maybe_sample'),
    ('paddle_tpu/fluid/executor.py', '_memviz.oom_incident'),
    ('paddle_tpu/fluid/parallel_executor.py', '_memviz.oom_incident'),
    ('paddle_tpu/fluid/trace.py', 'trace/counter_samples'),
    ('paddle_tpu/fluid/comms_plan.py', 'memviz.peak_bytes'),
    ('paddle_tpu/fluid/health.py', 'memviz.memory_pressure'),
    ('paddle_tpu/fluid/serving.py', 'register_scope_provider'),
    ('tools/stat_summary.py', 'memviz/live_bytes_total'),
    ('bench.py', 'memviz_overhead'),
    # auto-sharding planner (parallel/plan.py): plan build volume, the
    # priced-candidate table, the memviz HBM-gate rejections, the
    # unpriced-term honesty counter, the chosen-layout gauges, and the
    # digest folded into BOTH runner fingerprints —
    # tools/check_autoshard.py asserts the counters move on a real
    # two-process job with FLAGS_auto_shard=1
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_builds'),
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_candidates'),
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_hbm_rejected'),
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_unpriced'),
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_reused'),
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_params_sharded'),
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_layout_dp'),
    ('paddle_tpu/parallel/plan.py', 'parallel/plan_seconds'),
    ('paddle_tpu/fluid/parallel_executor.py', '_ashard.digest'),
    ('paddle_tpu/fluid/transpiler/collective.py',
     'auto_shard_plan.transpile_plan'),
    ('paddle_tpu/fluid/health.py', 'auto_shard_plan.report'),
    ('tools/stat_summary.py', 'parallel/plan_hbm_rejected'),
    ('bench.py', '_autoshard_fields'),
    # elastic resilience plane (fluid/elastic.py + fluid/faultinject.py
    # + the rpc/heartbeat retry satellites): crash-consistent store
    # volume, refusal accounting, the reshard schedule's predicted-vs-
    # measured honesty, staged-assembly waves, trainer re-admission,
    # heartbeat flap tolerance, rpc backoff, and the fault-injection
    # tallies — tools/check_elastic.py exercises the plane across real
    # process boundaries including a kill -9 mid-save
    ('paddle_tpu/fluid/elastic.py', 'elastic/checkpoints_saved'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/checkpoints_loaded'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/save_bytes'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/save_seconds'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/load_seconds'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/shards_written'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/last_generation'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/generations_pruned'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/refused_generations'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/refusal_dumps'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/reshard_params'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/reshard_wire_bytes'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/reshard_unpriced'),
    ('paddle_tpu/fluid/elastic.py',
     'elastic/reshard_predicted_seconds'),
    ('paddle_tpu/fluid/elastic.py',
     'elastic/reshard_measured_seconds'),
    ('paddle_tpu/fluid/elastic.py',
     'elastic/reshard_pred_over_measured'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/staging_waves'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/readmissions'),
    ('paddle_tpu/distributed/heartbeat.py', 'elastic/readmissions'),
    ('paddle_tpu/distributed/heartbeat.py',
     'elastic/heartbeat_flaps'),
    ('paddle_tpu/fluid/health.py', 'elastic/heartbeat_flaps'),
    ('paddle_tpu/fluid/faultinject.py', 'faultinject/armed'),
    ('paddle_tpu/fluid/faultinject.py', 'faultinject/hits'),
    ('paddle_tpu/fluid/faultinject.py', 'faultinject/fired'),
    ('paddle_tpu/distributed/rpc_ps.py', 'rpc/backoff_seconds'),
    ('paddle_tpu/distributed/rpc_ps.py', 'rpc_exhausted'),
    ('paddle_tpu/fluid/executor.py', '_finject.check'),
    ('paddle_tpu/fluid/parallel_executor.py', '_finject.check'),
    ('paddle_tpu/fluid/health.py', 'elastic.report'),
    ('bench.py', '_elastic_fields'),
    # self-healing supervisor (fluid/supervisor.py + the hung-step
    # watchdog + serving shedding satellites): decision volume, the
    # checkpoint plane's backpressure/stretch/torn-resave accounting,
    # confirmed deaths -> recoveries with lost-work totals, step
    # timeouts, and the serving deadline/degraded shed counters —
    # tools/check_supervisor.py and tools/check_chaos.py exercise the
    # whole loop across real process boundaries
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/decisions'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/checkpoints_taken'),
    ('paddle_tpu/fluid/supervisor.py',
     'supervisor/checkpoint_deferred'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/checkpoint_torn'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/cadence_stretched'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/save_seconds'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/snapshot_seconds'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/deaths_confirmed'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/recoveries'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/recovery_seconds'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/lost_steps'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/hung_steps'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/rejoins_admitted'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/frozen_intents'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/state_transitions'),
    ('paddle_tpu/fluid/supervisor.py', 'executor/step_timeouts'),
    ('paddle_tpu/fluid/executor.py', '_sup.guard_dispatch'),
    ('paddle_tpu/fluid/executor.py', '_sup.on_step_begin'),
    ('paddle_tpu/fluid/parallel_executor.py', '_sup.guard_dispatch'),
    ('paddle_tpu/fluid/serving.py', 'serving/shed_expired'),
    ('paddle_tpu/fluid/serving.py', 'serving/shed_degraded'),
    ('paddle_tpu/fluid/serving.py', 'serving/degraded'),
    ('paddle_tpu/fluid/elastic.py', 'elastic/rejoin_retries'),
    ('paddle_tpu/fluid/health.py', 'supervisor.report'),
    ('paddle_tpu/fluid/health.py', 'peer_health'),
    ('bench.py', '_chaos_fields'),
    # static Program verifier (fluid/progcheck.py): programs checked,
    # per-class diagnostic counters, seeded mutations, wall time —
    # tools/check_progcheck.py proves every class fires by name and
    # the /statusz verify section renders the report trail
    ('paddle_tpu/fluid/progcheck.py', 'verify/programs'),
    ('paddle_tpu/fluid/progcheck.py', 'verify/clean'),
    ('paddle_tpu/fluid/progcheck.py', 'verify/errors'),
    ('paddle_tpu/fluid/progcheck.py', 'verify/warnings'),
    ('paddle_tpu/fluid/progcheck.py', 'verify/diagnostics/'),
    ('paddle_tpu/fluid/progcheck.py', 'verify/seconds'),
    ('paddle_tpu/fluid/progcheck.py', 'verify/mutations'),
    ('paddle_tpu/fluid/executor.py', '_verify_plan_build'),
    ('paddle_tpu/fluid/executor.py', 'progcheck.mutate'),
    ('paddle_tpu/fluid/parallel_executor.py', 'FLAGS_program_verify'),
    ('paddle_tpu/fluid/transpiler/collective.py',
     'progcheck.verify_program'),
    ('paddle_tpu/fluid/transpiler/__init__.py',
     'progcheck.verify_program'),
    ('paddle_tpu/fluid/comms_plan.py', 'verify_buckets'),
    ('paddle_tpu/parallel/plan.py', 'progcheck.check_sharding'),
    ('paddle_tpu/fluid/health.py', 'progcheck.report'),
    # time-series telemetry plane (fluid/timeseries.py + fluid/slo.py):
    # the windowed-history sampler's own accounting, the job-history
    # retention at the aggregator, the SLO evaluator/alert counters,
    # and the step-boundary/heartbeat wiring that feeds them —
    # tools/check_timeseries.py exercises the plane against a live
    # two-process job
    ('paddle_tpu/fluid/timeseries.py', 'timeseries/samples'),
    ('paddle_tpu/fluid/timeseries.py', 'timeseries/sample_errors'),
    ('paddle_tpu/fluid/timeseries.py', 'timeseries/job_samples'),
    ('paddle_tpu/fluid/timeseries.py', 'timeseries/gap_points'),
    ('paddle_tpu/fluid/timeseries.py', 'timeseries/series'),
    ('paddle_tpu/fluid/slo.py', 'slo/objectives'),
    ('paddle_tpu/fluid/slo.py', 'slo/evals'),
    ('paddle_tpu/fluid/slo.py', 'slo/eval_errors'),
    ('paddle_tpu/fluid/slo.py', 'slo/alerts_fired'),
    ('paddle_tpu/fluid/slo.py', 'slo/alerts_resolved'),
    ('paddle_tpu/fluid/slo.py', 'slo/alerts_pending'),
    ('paddle_tpu/fluid/slo.py', 'slo/bad_clauses'),
    ('paddle_tpu/fluid/slo.py', 'slo/firing'),
    ('paddle_tpu/fluid/slo.py', 'supervisor.record_slo_breach'),
    ('paddle_tpu/fluid/executor.py', '_tseries.maybe_sample'),
    ('paddle_tpu/fluid/parallel_executor.py', '_tseries.maybe_sample'),
    ('paddle_tpu/fluid/health.py', 'timeseries.job_sample'),
    ('paddle_tpu/fluid/health.py', 'timeseries.job_gap'),
    ('paddle_tpu/fluid/health.py', 'timeseries.http_query'),
    ('paddle_tpu/fluid/health.py', 'slo.alertz'),
    ('paddle_tpu/fluid/supervisor.py', 'supervisor/decision/slo_breach'),
    ('paddle_tpu/fluid/trace.py', 'trace/dumps_suppressed'),
    ('paddle_tpu/fluid/serving.py', 'FLAGS_serving_slo_p99_s'),
    ('tools/stat_summary.py', 'ts.counter_deltas'),
    ('bench.py', 'append_history'),
    # closed-loop autopilot (fluid/autopilot.py): the bounded decision
    # log, the online comms refits and their freeze/interlock/revert
    # accounting, the degenerate-refit guard in the fitter, and the
    # serving-side ladder adaptation counters —
    # tools/check_autopilot.py closes the loop against a live
    # faultinjected drift
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/decisions'),
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/decision/'),
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/refits'),
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/frozen_intents'),
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/slo_frozen'),
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/reverts'),
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/engaged'),
    ('paddle_tpu/fluid/autopilot.py', 'autopilot/persist_errors'),
    ('paddle_tpu/fluid/timeseries.py', 'autopilot/tick_errors'),
    ('paddle_tpu/fluid/comms.py', 'autopilot/refit_degenerate'),
    ('paddle_tpu/fluid/comms.py', 'comms/plan_pred_over_measured'),
    ('paddle_tpu/fluid/serving.py', 'serving/bucket_dropped'),
    ('paddle_tpu/fluid/serving.py', 'serving/bucket_prewarmed'),
    ('paddle_tpu/fluid/serving.py', 'serving/pad_waste_ratio'),
    ('paddle_tpu/fluid/serving.py', 'serving/close_wait_holds'),
    # serving fleet (fluid/fleet.py): the cross-replica router's
    # decision log, sticky routing, class policy and priced
    # eviction/migration accounting — tools/check_fleet.py closes the
    # loop against a live two-replica skewed soak
    ('paddle_tpu/fluid/fleet.py', 'fleet/decisions'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/decision/'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/frozen_intents'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/routed_requests'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/placements'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/migrations'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/evictions'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/reverts'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/ticks'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/class_shed'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/class_restored'),
    ('paddle_tpu/fluid/fleet.py', 'fleet/replicas'),
    ('paddle_tpu/fluid/timeseries.py', 'fleet/tick_errors'),
    ('paddle_tpu/fluid/serving.py', 'serving/shed_class'),
    ('paddle_tpu/fluid/serving.py', 'serving/tenant_evicted'),
    ('paddle_tpu/fluid/serving.py', 'serving/warmup_buckets'),
    ('paddle_tpu/fluid/health.py', "'fleet':"),
    # op-cost attribution plane (fluid/opprof.py): segment snapshots +
    # eager replays, the attributed-vs-unattributed ms honesty split,
    # capture event consumption with the dropped-row counter, and the
    # ranked kernel-worklist gauge — tools/check_opprof.py closes the
    # loop against a warmed LeNet with the 10% step-report agreement
    # band
    ('paddle_tpu/fluid/opprof.py', 'opprof/snapshots'),
    ('paddle_tpu/fluid/opprof.py', 'opprof/replays'),
    ('paddle_tpu/fluid/opprof.py', 'opprof/instances'),
    ('paddle_tpu/fluid/opprof.py', 'opprof/attributed_ms_total'),
    ('paddle_tpu/fluid/opprof.py', 'opprof/unattributed_ms_total'),
    ('paddle_tpu/fluid/opprof.py', 'opprof/capture_events'),
    ('paddle_tpu/fluid/opprof.py', 'opprof/dropped_events'),
    ('paddle_tpu/fluid/opprof.py', 'opprof/worklist_candidates'),
    ('paddle_tpu/fluid/executor.py', '_opprof.want_snapshot'),
    ('paddle_tpu/fluid/executor.py', '_opprof.note_segment'),
    ('paddle_tpu/fluid/profiler.py', 'profiler/dropped_events'),
    ('paddle_tpu/fluid/health.py', "'op_costs':"),
    ('tools/stat_summary.py', 'opprof/worklist_candidates'),
    ('bench.py', 'opprof_overhead'),
]


def main():
    missing = []
    for rel, needle in REQUIRED:
        path = os.path.join(ROOT, rel)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            missing.append('%s: FILE MISSING (needed %r)'
                           % (rel, needle))
            continue
        if needle not in src:
            missing.append('%s: instrument point %r disappeared'
                           % (rel, needle))
    print('stat instrument points: %d required, %d present'
          % (len(REQUIRED), len(REQUIRED) - len(missing)))
    if missing:
        for m in missing:
            print('MISSING  ' + m)
        return 1
    print('coverage: complete')
    return 0


if __name__ == '__main__':
    sys.exit(main())
