"""Pure-JAX platform-ceiling train steps for the non-ResNet BASELINE
configs (round-4 VERDICT item 2): what a hand-tuned JAX user would
write with no framework in the loop, same batch/precision/optimizer as
the matching bench.py entry.  The gap bench-vs-ceiling isolates
framework overhead from platform limits, like
tools/jax_resnet_ceiling.py does for config 1.

  python tools/jax_ceilings.py bert  [--batch 32] [--seq 128]
  python tools/jax_ceilings.py bert  --batch 4 --seq 2048   # flash
  python tools/jax_ceilings.py widedeep [--batch 2048]
  python tools/jax_ceilings.py nmt   [--batch 32]

AMP semantics mirror the bench programs: bf16 activations with f32
MASTER weights (params cast to bf16 at use), f32 Adam/Adagrad, dynamic
loss scaling (scale the loss, all-finite check over grads, skip-or-
apply + scale update) for bert/nmt.  Sync style: np.asarray value
fetch (block_until_ready alone times dispatch through the tunnel).
"""
import argparse
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

BF16 = jnp.bfloat16


# ---------------------------------------------------------------- common

def dense(x, w, b=None):
    y = x @ w.astype(x.dtype)
    if b is not None:
        y = y + b.astype(x.dtype)
    return y


def layer_norm(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, -1, keepdims=True)
    v = jnp.mean(jnp.square(xf - m), -1, keepdims=True)
    y = (xf - m) * jax.lax.rsqrt(v + eps) * g + b
    return y.astype(x.dtype)


def dropout(x, rate, key):
    if not rate:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def adam_init(params):
    zeros = lambda p: jax.tree.map(jnp.zeros_like, p)
    return {'m': zeros(params), 'v': zeros(params),
            't': jnp.zeros((), jnp.float32)}


def adam_apply(params, grads, st, lr=1e-4, b1=0.9, b2=0.999, eps=1e-8):
    t = st['t'] + 1.0
    m = jax.tree.map(lambda a, g: b1 * a + (1 - b1) * g, st['m'], grads)
    v = jax.tree.map(lambda a, g: b2 * a + (1 - b2) * g * g,
                     st['v'], grads)
    corr = jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
    new = jax.tree.map(
        lambda p, mm, vv: p - lr * corr * mm / (jnp.sqrt(vv) + eps),
        params, m, v)
    return new, {'m': m, 'v': v, 't': t}


def scaled_step(loss_fn, params, opt_state, scale, *args):
    """Dynamic-loss-scaling step (the AMP decorate semantics): scale
    the loss, unscale grads, all-finite check gates the update, scale
    doubles every 1000 good steps / halves on overflow."""
    def scaled_loss(p):
        return loss_fn(p, *args).astype(jnp.float32) * scale['s']
    loss, grads = jax.value_and_grad(scaled_loss)(params)
    grads = jax.tree.map(lambda g: g.astype(jnp.float32) / scale['s'],
                         grads)
    finite = jnp.all(jnp.stack([jnp.all(jnp.isfinite(g))
                                for g in jax.tree.leaves(grads)]))
    new_params, new_opt = adam_apply(params, grads, opt_state)
    params = jax.tree.map(lambda a, b: jnp.where(finite, a, b),
                          new_params, params)
    opt_state = jax.tree.map(lambda a, b: jnp.where(finite, a, b),
                             new_opt, opt_state)
    good = jnp.where(finite, scale['good'] + 1, 0)
    s = jnp.where(finite,
                  jnp.where(good >= 1000, scale['s'] * 2.0, scale['s']),
                  scale['s'] * 0.5)
    good = jnp.where(good >= 1000, 0, good)
    return loss / scale['s'], params, opt_state, {'s': s, 'good': good}


def _sync(state):
    """Close the async-dispatch window by fetching the SMALLEST state
    leaf (a scalar: adam t / scale / step counter).  Fetching a big
    leaf would pull it over the tunnel (~12 MB/s) and time the wire —
    the first-draft bug that made every ceiling look 6x slow: syncing
    on the [30522,768] embedding shipped 94 MB per sync."""
    leaves = jax.tree.leaves(state)
    np.asarray(min(leaves, key=lambda a: getattr(a, 'size', 1 << 60)))


def timeit(step, state, steps, feed):
    # device-resident feeds AND initial state, like bench._timed_steps:
    # shipping numpy per call forces synchronous tunnel transfers and
    # an avals-changed recompile on the numpy->Array transition
    feed = tuple(jax.device_put(np.asarray(f)) for f in feed)
    state = jax.tree.map(jax.device_put, state)
    state = step(state, *feed)  # warm/compile
    _sync(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state = step(state, *feed)
    _sync(state)
    return (time.perf_counter() - t0) / steps


# ---------------------------------------------------------------- bert

def run_bert(batch, seq, steps, ablate=(), n_layers=12):
    V, H, L, NH, FF, TV = 30522, 768, n_layers, 12, 3072, 2
    D = H // NH
    drop = 0.0 if 'dropout' in ablate else 0.1
    attn_drop = (0.1 if seq < 512 else 0.0) if 'dropout' not in ablate \
        else 0.0
    use_flash = seq >= 512
    rng = np.random.RandomState(0)

    def w(*shape):
        return (rng.randn(*shape) * 0.02).astype(np.float32)

    params = {'emb': w(V, H), 'pos': w(seq, H), 'sent': w(TV, H),
              'ln0_g': np.ones(H, np.float32),
              'ln0_b': np.zeros(H, np.float32),
              'mlm_w': w(H, V), 'mlm_b': np.zeros(V, np.float32),
              'nsp_w': w(H, 2), 'nsp_b': np.zeros(2, np.float32)}
    for i in range(L):
        params.update({
            'l%d_qkv' % i: w(H, 3 * H),
            'l%d_qkv_b' % i: np.zeros(3 * H, np.float32),
            'l%d_o' % i: w(H, H), 'l%d_o_b' % i: np.zeros(H, np.float32),
            'l%d_ln1_g' % i: np.ones(H, np.float32),
            'l%d_ln1_b' % i: np.zeros(H, np.float32),
            'l%d_f1' % i: w(H, FF), 'l%d_f1_b' % i: np.zeros(FF,
                                                            np.float32),
            'l%d_f2' % i: w(FF, H), 'l%d_f2_b' % i: np.zeros(H,
                                                             np.float32),
            'l%d_ln2_g' % i: np.ones(H, np.float32),
            'l%d_ln2_b' % i: np.zeros(H, np.float32)})

    ids = rng.randint(0, V, (batch, seq)).astype('int32')
    sent = np.zeros((batch, seq), 'int32')
    mlm = np.where(rng.rand(batch, seq) < 0.15,
                   rng.randint(0, V, (batch, seq)), -1).astype('int32')
    nsp = rng.randint(0, 2, (batch,)).astype('int32')

    if use_flash:
        import os
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from paddle_tpu.ops.pallas.flash_attention import flash_attention

    def attention(x, p, i, key, key_bias):
        qkv = dense(x, p['l%d_qkv' % i], p['l%d_qkv_b' % i])
        q, k, v = jnp.split(qkv, 3, -1)
        q, k, v = [a.reshape(batch, seq, NH, D) for a in (q, k, v)]
        if use_flash:
            # the framework's bench passes the input mask as the flash
            # key bias; ride it as a runtime arg so the ceiling pays
            # the same per-block bias add + dbias backward
            ctx = flash_attention(q, k, v, min_seq=0,
                                  key_bias=key_bias)
        else:
            s = jnp.einsum('bthd,bshd->bhts', q, k,
                           preferred_element_type=jnp.float32) / D ** 0.5
            # the framework's naive chain adds the input-mask bias too
            s = s + key_bias[:, None, None, :]
            pr = jax.nn.softmax(s, -1).astype(x.dtype)
            pr = dropout(pr, attn_drop, key)
            ctx = jnp.einsum('bhts,bshd->bthd', pr, v)
        return dense(ctx.reshape(batch, seq, H), p['l%d_o' % i],
                     p['l%d_o_b' % i])

    def loss_fn(p, ids, sent_ids, mlm_label, nsp_label, key_bias,
                step_key):
        x = (p['emb'][ids] + p['pos'][None, :, :] +
             p['sent'][sent_ids]).astype(BF16)
        x = layer_norm(x, p['ln0_g'], p['ln0_b'])
        keys = jax.random.split(step_key, 3 * L)
        for i in range(L):
            a = dropout(attention(x, p, i, keys[3 * i], key_bias),
                        drop, keys[3 * i + 1])
            x = layer_norm(x + a, p['l%d_ln1_g' % i], p['l%d_ln1_b' % i])
            f = dense(x, p['l%d_f1' % i], p['l%d_f1_b' % i])
            f = jax.nn.gelu(f, approximate=False)
            f = dense(f, p['l%d_f2' % i], p['l%d_f2_b' % i])
            f = dropout(f, drop, keys[3 * i + 2])
            x = layer_norm(x + f, p['l%d_ln2_g' % i], p['l%d_ln2_b' % i])
        if 'head' in ablate:
            mlm_loss = jnp.mean(jnp.square(x.astype(jnp.float32)))
        else:
            logits = dense(x, p['mlm_w'],
                           p['mlm_b']).astype(jnp.float32)
            lp = jax.nn.log_softmax(logits, -1)
            tgt = jnp.maximum(mlm_label, 0)
            nll = -jnp.take_along_axis(lp, tgt[..., None], -1)[..., 0]
            maskd = (mlm_label >= 0).astype(jnp.float32)
            mlm_loss = jnp.sum(nll * maskd) / \
                jnp.maximum(jnp.sum(maskd), 1)
        cls = x[:, 0, :]
        nl = dense(cls, p['nsp_w'], p['nsp_b']).astype(jnp.float32)
        nlp = jax.nn.log_softmax(nl, -1)
        nsp_loss = -jnp.mean(
            jnp.take_along_axis(nlp, nsp_label[:, None], -1))
        return mlm_loss + nsp_loss

    opt = adam_init(params)
    scale = {'s': jnp.float32(32768.0), 'good': jnp.zeros((), jnp.int32)}

    @jax.jit
    def step(state, ids, sent_ids, mlm_label, nsp_label, key_bias):
        params, opt, scale, it = state
        key = jax.random.fold_in(jax.random.PRNGKey(0), it)
        if 'scaling' in ablate:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, ids, sent_ids, mlm_label, nsp_label, key_bias,
                key)
            params, opt = adam_apply(params, grads, opt)
        else:
            loss, params, opt, scale = scaled_step(
                loss_fn, params, opt, scale, ids, sent_ids, mlm_label,
                nsp_label, key_bias, key)
        return (params, opt, scale, it + 1)

    state = (params, opt, scale, jnp.zeros((), jnp.int32))
    key_bias = np.zeros((batch, seq), np.float32)  # used on flash path
    dt = timeit(step, state, steps, (ids, sent, mlm, nsp, key_bias))
    print('bert ceiling b%d s%d%s: %.2f ms/step (%.1f seq/s)'
          % (batch, seq,
             (' -' + ','.join(sorted(ablate))) if ablate else '',
             dt * 1e3, batch / dt))


# ------------------------------------------------------------ wide&deep

def run_widedeep(batch, steps):
    VOC, EMB, NS, ND = 1000, 16, 26, 13
    HID = (400, 400, 400)
    rng = np.random.RandomState(0)
    params = {'demb': (rng.randn(VOC, EMB) * 0.02).astype(np.float32),
              'wemb': (rng.randn(VOC, 1) * 0.02).astype(np.float32),
              'wd': (rng.randn(ND, 1) * 0.05).astype(np.float32)}
    last = ND + NS * EMB
    for i, h in enumerate(HID):
        params['h%d' % i] = (rng.randn(last, h) *
                             (2.0 / last) ** 0.5).astype(np.float32)
        params['h%d_b' % i] = np.zeros(h, np.float32)
        last = h
    params['out'] = (rng.randn(last, 1) * 0.05).astype(np.float32)
    params['out_b'] = np.zeros(1, np.float32)

    dense_x = rng.rand(batch, ND).astype('float32')
    sparse_x = rng.randint(0, VOC, (batch, NS)).astype('int32')
    label = rng.randint(0, 2, (batch, 1)).astype('float32')

    def loss_fn(p, dense_x, sparse_x, label):
        emb = p['demb'][sparse_x].reshape(batch, NS * EMB)
        x = jnp.concatenate([dense_x, emb], 1)
        for i in range(len(HID)):
            x = jax.nn.relu(x @ p['h%d' % i] + p['h%d_b' % i])
        deep = x @ p['out'] + p['out_b']
        wide = jnp.sum(p['wemb'][sparse_x], 1) + dense_x @ p['wd']
        logit = deep + wide
        return jnp.mean(
            jnp.maximum(logit, 0) - logit * label +
            jnp.log1p(jnp.exp(-jnp.abs(logit))))

    acc = jax.tree.map(jnp.zeros_like, params)

    @jax.jit
    def step(state, dense_x, sparse_x, label):
        p, acc = state
        g = jax.grad(loss_fn)(p, dense_x, sparse_x, label)
        acc = jax.tree.map(lambda a, gg: a + gg * gg, acc, g)
        p = jax.tree.map(
            lambda pp, gg, aa: pp - 0.01 * gg / (jnp.sqrt(aa) + 1e-6),
            p, g, acc)
        return (p, acc)

    dt = timeit(step, (params, acc), steps, (dense_x, sparse_x, label))
    print('wide&deep ceiling b%d: %.2f ms/step (%.0f ex/s)'
          % (batch, dt * 1e3, batch / dt))


# ------------------------------------------------------------------ nmt

def run_nmt(batch, steps, src_len=64, tgt_len=64):
    # FAITHFUL to models/transformer.py + bench_transformer: fc biases
    # everywhere, dropout on embeddings / attention probs / ffn mid
    # (18+ sites), additive pad bias on encoder scores, post-LN, label
    # smoothing, AMP + dynamic loss scaling, Adam
    V, H, NH, FF, L = 10000, 512, 8, 2048, 6
    D = H // NH
    drop = 0.1
    eps_ls = 0.1
    rng = np.random.RandomState(0)

    def w(*shape):
        return (rng.randn(*shape) * 0.02).astype(np.float32)

    def b(n):
        return np.zeros(n, np.float32)

    params = {'semb': w(V, H), 'temb': w(V, H), 'proj': w(H, V),
              'proj_b': b(V)}
    for side, n in (('e', L), ('d', L)):
        for i in range(n):
            pre = '%s%d_' % (side, i)
            params.update({pre + 'qkv': w(H, 3 * H),
                           pre + 'qkv_b': b(3 * H),
                           pre + 'o': w(H, H), pre + 'o_b': b(H),
                           pre + 'ln1g': np.ones(H, np.float32),
                           pre + 'ln1b': np.zeros(H, np.float32),
                           pre + 'f1': w(H, FF), pre + 'f1_b': b(FF),
                           pre + 'f2': w(FF, H), pre + 'f2_b': b(H),
                           pre + 'ln2g': np.ones(H, np.float32),
                           pre + 'ln2b': np.zeros(H, np.float32)})
            if side == 'd':
                params.update({pre + 'xq': w(H, H), pre + 'xq_b': b(H),
                               pre + 'xk': w(H, H), pre + 'xk_b': b(H),
                               pre + 'xv': w(H, H), pre + 'xv_b': b(H),
                               pre + 'xo': w(H, H), pre + 'xo_b': b(H),
                               pre + 'ln3g': np.ones(H, np.float32),
                               pre + 'ln3b': np.zeros(H, np.float32)})

    src = rng.randint(0, V, (batch, src_len)).astype('int32')
    tgt = rng.randint(0, V, (batch, tgt_len)).astype('int32')
    lab = rng.randint(0, V, (batch, tgt_len)).astype('int32')

    def posenc(t):
        pos = np.arange(t)[:, None]
        i = np.arange(H)[None, :]
        ang = pos / np.power(10000, (2 * (i // 2)) / H)
        pe = np.where(i % 2 == 0, np.sin(ang), np.cos(ang))
        return jnp.asarray(pe, BF16)


    def mha(q_in, kv_in, wqkv, wo, causal, key, xattn=None,
            bias=None):
        if xattn is None:
            qkv = dense(q_in, wqkv[0], wqkv[1])
            q, k, v = jnp.split(qkv, 3, -1)
        else:
            (wq, bq_), (wk, bk_), (wv, bv_) = xattn
            q = dense(q_in, wq, bq_)
            k = dense(kv_in, wk, bk_)
            v = dense(kv_in, wv, bv_)
        b, tq = q.shape[:2]
        tk = k.shape[1]
        q = q.reshape(b, tq, NH, D)
        k = k.reshape(b, tk, NH, D)
        v = v.reshape(b, tk, NH, D)
        s = jnp.einsum('bthd,bshd->bhts', q, k,
                       preferred_element_type=jnp.float32) / D ** 0.5
        if bias is not None:
            s = s + bias
        if causal:
            mask = jnp.tril(jnp.ones((tq, tk), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, -1).astype(q_in.dtype)
        p = dropout(p, drop, key)
        ctx = jnp.einsum('bhts,bshd->bthd', p, v).reshape(b, tq, H)
        return dense(ctx, wo[0], wo[1])

    def loss_fn(p, src, tgt, lab, pad_bias, key):
        keys = jax.random.split(key, 8 * L + 2)
        kc = iter(range(8 * L))
        x = (p['semb'][src].astype(BF16) * (H ** 0.5) +
             posenc(src_len)[None])
        x = dropout(x, drop, keys[-1])
        for i in range(L):
            pre = 'e%d_' % i
            a = mha(x, x, (p[pre + 'qkv'], p[pre + 'qkv_b']),
                    (p[pre + 'o'], p[pre + 'o_b']), False,
                    keys[next(kc)], bias=pad_bias)
            x = layer_norm(x + a, p[pre + 'ln1g'], p[pre + 'ln1b'])
            f = jax.nn.relu(dense(x, p[pre + 'f1'], p[pre + 'f1_b']))
            f = dropout(f, drop, keys[next(kc)])
            f = dense(f, p[pre + 'f2'], p[pre + 'f2_b'])
            x = layer_norm(x + f, p[pre + 'ln2g'], p[pre + 'ln2b'])
        mem = x
        y = (p['temb'][tgt].astype(BF16) * (H ** 0.5) +
             posenc(tgt_len)[None])
        y = dropout(y, drop, keys[-2])
        for i in range(L):
            pre = 'd%d_' % i
            a = mha(y, y, (p[pre + 'qkv'], p[pre + 'qkv_b']),
                    (p[pre + 'o'], p[pre + 'o_b']), True,
                    keys[next(kc)])
            y = layer_norm(y + a, p[pre + 'ln1g'], p[pre + 'ln1b'])
            xa = mha(y, mem, None,
                     (p[pre + 'xo'], p[pre + 'xo_b']), False,
                     keys[next(kc)],
                     xattn=((p[pre + 'xq'], p[pre + 'xq_b']),
                            (p[pre + 'xk'], p[pre + 'xk_b']),
                            (p[pre + 'xv'], p[pre + 'xv_b'])),
                     bias=pad_bias)
            y = layer_norm(y + xa, p[pre + 'ln3g'], p[pre + 'ln3b'])
            f = jax.nn.relu(dense(y, p[pre + 'f1'], p[pre + 'f1_b']))
            f = dropout(f, drop, keys[next(kc)])
            f = dense(f, p[pre + 'f2'], p[pre + 'f2_b'])
            y = layer_norm(y + f, p[pre + 'ln2g'], p[pre + 'ln2b'])
        logits = dense(y, p['proj'], p['proj_b']).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits, -1)
        smooth = (1 - eps_ls)
        nll = -jnp.take_along_axis(lp, lab[..., None], -1)[..., 0]
        uniform = -jnp.mean(lp, -1)
        return jnp.mean(smooth * nll + eps_ls * uniform)

    opt = adam_init(params)
    scale = {'s': jnp.float32(32768.0), 'good': jnp.zeros((), jnp.int32)}

    @jax.jit
    def step(state, src, tgt, lab, pad_bias):
        params, opt, scale, it = state
        key = jax.random.fold_in(jax.random.PRNGKey(0), it)
        loss, params, opt, scale = scaled_step(
            loss_fn, params, opt, scale, src, tgt, lab, pad_bias, key)
        return (params, opt, scale, it + 1)

    state = (params, opt, scale, jnp.zeros((), jnp.int32))
    # pad bias rides as a RUNTIME argument: a captured zeros constant
    # would be algebraically deleted by XLA and the ceiling would not
    # pay the add+broadcast the framework model pays
    pad_bias_np = np.zeros((batch, 1, 1, src_len), np.float32)
    dt = timeit(step, state, steps, (src, tgt, lab, pad_bias_np))
    print('nmt ceiling b%d %d/%d: %.2f ms/step (%.0f tok/s)'
          % (batch, src_len, tgt_len, dt * 1e3,
             batch * tgt_len / dt))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('which', choices=['bert', 'widedeep', 'nmt'])
    ap.add_argument('--batch', type=int, default=None)
    ap.add_argument('--seq', type=int, default=128)
    ap.add_argument('--steps', type=int, default=20)
    ap.add_argument('--ablate', default='',
                    help='comma list: dropout,head,scaling')
    ap.add_argument('--layers', type=int, default=12)
    args = ap.parse_args()
    if args.which == 'bert':
        run_bert(args.batch or 32, args.seq, args.steps,
                 ablate=tuple(a for a in args.ablate.split(',') if a),
                 n_layers=args.layers)
    elif args.which == 'widedeep':
        run_widedeep(args.batch or 2048, args.steps)
    else:
        run_nmt(args.batch or 32, args.steps)


if __name__ == '__main__':
    main()
