"""Export a chrome://tracing file from a captured profile.

Reference: tools/timeline.py converts the profiler's protobuf dump into
chrome-trace JSON.  The jax profiler (fluid.profiler wraps it) already
emits a gzipped chrome trace inside its plugin directory; this tool
locates it and writes a plain .json chrome://tracing / Perfetto can
open directly.

Usage: python tools/timeline.py --profile_path /tmp/profile \
           --timeline_path /tmp/timeline.json
"""

import argparse
import glob
import gzip
import os
import shutil
import sys


def find_trace(profile_path):
    pats = [os.path.join(profile_path, '**', '*.trace.json.gz'),
            os.path.join(profile_path, '**', '*.trace.json')]
    hits = []
    for p in pats:
        hits.extend(glob.glob(p, recursive=True))
    if not hits:
        raise SystemExit(
            'no trace found under %s — capture one with '
            'fluid.profiler.start_trace(logdir)/stop_trace() around '
            'the steps to convert' % profile_path)
    return max(hits, key=os.path.getmtime)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--profile_path', default='/tmp/profile')
    ap.add_argument('--timeline_path', default='/tmp/timeline.json')
    args = ap.parse_args()
    src = find_trace(args.profile_path)
    if src.endswith('.gz'):
        with gzip.open(src, 'rb') as f_in, \
                open(args.timeline_path, 'wb') as f_out:
            shutil.copyfileobj(f_in, f_out)
    else:
        shutil.copy(src, args.timeline_path)
    print('chrome trace written to %s (open in chrome://tracing or '
          'https://ui.perfetto.dev)' % args.timeline_path)
    return 0


if __name__ == '__main__':
    sys.exit(main())
