"""Export a chrome://tracing file from a captured profile, merging the
fluid.trace host spans with the device trace when both exist.

Reference: tools/timeline.py converts the profiler's protobuf dump into
chrome-trace JSON.  The jax profiler (fluid.profiler wraps it) already
emits a gzipped chrome trace inside its plugin directory; this tool
locates it and writes a plain .json chrome://tracing / Perfetto can
open directly.  Since the fluid.trace PR, `fluid.profiler.start_trace`
also rides the span tracer along and `stop_trace` drops the host spans
as `<logdir>/host_trace.json` — when that file is present (or passed
via --host_trace), the output is ONE merged timeline: device kernels
on their original pids, host phase spans (bind / feed_h2d / dispatch /
compile / reader_wait / fetch_d2h) on a 'paddle_tpu host' process,
aligned on the pt_clock_sync annotation the capture emitted.

Usage: python tools/timeline.py --profile_path /tmp/profile \
           --timeline_path /tmp/timeline.json [--host_trace host.json]

Job mode (`--job`) merges a whole MULTI-WORKER job instead: it pulls
every worker's /trace/dump over HTTP (--workers 'rank=host:port,...',
default $PADDLE_TPU_STATUS_WORKERS — the launcher's wire format) or
reads already-saved dump files (--dumps a.json b.json ...), re-homes
each rank's clock onto the shared unix-epoch anchor its dump carries,
and writes ONE Perfetto timeline with per-rank process tracks plus the
cross-rank skew report (fluid.trace.collect_job).

Usage: python tools/timeline.py --job --workers 0=h:9184,1=h:9185 \
           --timeline_path /tmp/job_timeline.json
       python tools/timeline.py --job --dumps w0.json w1.json \
           --timeline_path /tmp/job_timeline.json

Op mode (`--ops`) attributes a capture's device-kernel time back to
per-INSTANCE fluid op descs through fluid.opprof (capture taken with
FLAGS_opprof on, so scope names carry the '#<block-index>' suffix):
it feeds the capture — or an already-merged timeline (--timeline as
input) — through opprof.record_capture, prints the ranked table with
type/layer rollups and the honest unattributed remainder, and can
emit the kernel worklist (--worklist op_worklist.json).

Usage: python tools/timeline.py --ops --profile_path /tmp/profile \
           [--steps N] [--worklist op_worklist.json]
"""

import argparse
import glob
import gzip
import json
import os
import shutil
import sys


def find_trace(profile_path):
    pats = [os.path.join(profile_path, '**', '*.trace.json.gz'),
            os.path.join(profile_path, '**', '*.trace.json')]
    hits = []
    for p in pats:
        hits.extend(h for h in glob.glob(p, recursive=True)
                    if not h.endswith('host_trace.json'))
    if not hits:
        raise SystemExit(
            'no trace found under %s — capture one with '
            'fluid.profiler.start_trace(logdir)/stop_trace() around '
            'the steps to convert' % profile_path)
    return max(hits, key=os.path.getmtime)


def find_host_trace(profile_path):
    hits = glob.glob(os.path.join(profile_path, '**', 'host_trace.json'),
                     recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def load_device_events(src):
    opener = gzip.open if src.endswith('.gz') else open
    with opener(src, 'rt') as f:
        return json.load(f).get('traceEvents', [])


def merge(src, host_path, out_path):
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from paddle_tpu.fluid import trace as pt_trace
    with open(host_path) as f:
        host = json.load(f)
    merged = pt_trace.merge_device_trace(
        host.get('ptHostEvents', []), load_device_events(src),
        sync_host_us=host.get('ptSync'),
        capture_t0_us=host.get('ptCaptureT0'))
    pt_trace.write_chrome(out_path, merged)
    n_host = sum(1 for e in host.get('ptHostEvents', [])
                 if e.get('ph') == 'X')
    # counter tracks (memviz live-HBM classes) ride the host events
    # as 'C' samples; surface their presence so a silently-dark
    # memory axis is visible at merge time
    n_counters = sum(1 for e in host.get('ptHostEvents', [])
                     if e.get('ph') == 'C')
    return n_host, n_counters


def collect_job_cli(args):
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from paddle_tpu.fluid import trace as pt_trace
    if args.dumps:
        workers = [(str(i), p) for i, p in enumerate(args.dumps)]

        def fetch(path):
            with open(path) as f:
                return f.read()
    else:
        spec = args.workers or os.environ.get(
            'PADDLE_TPU_STATUS_WORKERS', '')
        if not spec:
            raise SystemExit(
                '--job needs --workers rank=host:port,... (or '
                'PADDLE_TPU_STATUS_WORKERS) or --dumps file.json ...')
        workers = spec
        fetch = None
    doc = pt_trace.collect_job(workers=workers, fetch=fetch,
                               out_path=args.timeline_path)
    job = doc.get('ptJob', {})
    n = sum(1 for e in doc['traceEvents'] if e.get('ph') == 'X')
    print('merged job timeline written to %s (%d ranks, %d span '
          'events; open in https://ui.perfetto.dev)'
          % (args.timeline_path, len(job.get('workers', {})), n))
    for rank, err in sorted(job.get('skipped', {}).items()):
        print('  SKIPPED rank %s: %s' % (rank, err))
    skew = job.get('skew')
    if skew:
        wall = skew['wall']
        print('  skew: slowest rank %s at p50 %.3f ms, %.2fx the '
              'cross-rank median (%.3f ms)'
              % (wall['slowest_rank'], wall['max_p50_ms'],
                 wall['skew_ratio'], wall['median_p50_ms']))
        worst = sorted(skew['phases'].items(),
                       key=lambda kv: -kv[1]['ratio'])[:3]
        for name, ph in worst:
            print('    phase %-14s rank %s %.3f ms/step '
                  '(%.2fx median)' % (name, ph['slowest_rank'],
                                      ph['max_ms'], ph['ratio']))
    return 0


def ops_cli(args):
    """--ops: per-instance op attribution of a capture or merged
    timeline via fluid.opprof (no device needed — pure event math)."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.fluid import opprof
    if os.path.isfile(args.timeline_path):
        with open(args.timeline_path) as f:
            events = json.load(f).get('traceEvents', [])
        src_label = args.timeline_path
    else:
        src = find_trace(args.profile_path)
        opener = gzip.open if src.endswith('.gz') else open
        with opener(src, 'rt') as f:
            events = json.load(f).get('traceEvents', [])
        src_label = src
    res = opprof.record_capture(events, program='capture',
                                steps=max(args.steps, 1))
    rep = opprof.report()
    print('op attribution of %s (%d segment groups, %d malformed '
          'rows dropped):' % (src_label, res['segments'],
                              res['dropped']))
    print('%-34s %-22s %10s %8s %7s' %
          ('instance', 'segment', 'ms/step', 'calls', 'share'))
    for row in rep['top']:
        print('%-34s %-22s %10.4f %8d %6.2f%%'
              % (row['instance'], row['segment'][:22],
                 row['ms_per_step'], row['calls'], row['share_pct']))
    if rep['unattributed_ms']:
        print('unattributed: %.4f ms/step' % rep['unattributed_ms'])
    print('by type: ' + ', '.join(
        '%s=%.3fms' % (t, v['ms_per_step']) for t, v in sorted(
            rep['by_type'].items(),
            key=lambda kv: -kv[1]['ms_per_step'])[:8]))
    by_layer = rep['by_layer']
    if by_layer:
        print('by layer: ' + ', '.join(
            '%s=%.3fms' % (l, v) for l, v in sorted(
                by_layer.items(), key=lambda kv: -kv[1])[:8]))
    if args.worklist:
        path = opprof.write_worklist(args.worklist)
        print('kernel worklist written to %s' % path)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--profile_path', default='/tmp/profile')
    ap.add_argument('--timeline_path', default='/tmp/timeline.json')
    ap.add_argument('--host_trace', default=None,
                    help='host_trace.json written by fluid.profiler.'
                         'stop_trace (default: auto-discover under '
                         'profile_path)')
    ap.add_argument('--job', action='store_true',
                    help='merge a multi-worker job from /trace/dump '
                         'scrapes (--workers) or saved dump files '
                         '(--dumps) into one per-rank timeline')
    ap.add_argument('--workers', default=None,
                    help="job worker spec 'rank=host:port,...' "
                         '(default: $PADDLE_TPU_STATUS_WORKERS)')
    ap.add_argument('--dumps', nargs='*', default=None,
                    help='merge saved /trace/dump files instead of '
                         'scraping (each dump\'s own ptRank labels '
                         'it; argument order is the fallback)')
    ap.add_argument('--ops', action='store_true',
                    help='attribute device-kernel time to per-'
                         'instance fluid op descs (fluid.opprof) '
                         'from the capture under --profile_path, or '
                         'from an existing merged timeline when '
                         '--timeline_path names a file')
    ap.add_argument('--steps', type=int, default=1,
                    help='--ops: steps the capture spans (totals '
                         'divide by this for per-step costs)')
    ap.add_argument('--worklist', default=None,
                    help='--ops: also write the ranked kernel '
                         'worklist JSON here')
    args = ap.parse_args()
    if args.ops:
        return ops_cli(args)
    if args.job:
        return collect_job_cli(args)
    src = find_trace(args.profile_path)
    host_path = args.host_trace or find_host_trace(args.profile_path)
    if host_path:
        n_host, n_counters = merge(src, host_path, args.timeline_path)
        print('merged chrome trace written to %s (%d host spans + '
              '%d counter samples + device events; open in '
              'chrome://tracing or https://ui.perfetto.dev)'
              % (args.timeline_path, n_host, n_counters))
        return 0
    # device-only capture: passthrough, byte-identical to the source
    if src.endswith('.gz'):
        with gzip.open(src, 'rb') as f_in, \
                open(args.timeline_path, 'wb') as f_out:
            shutil.copyfileobj(f_in, f_out)
    else:
        shutil.copy(src, args.timeline_path)
    print('chrome trace written to %s (open in chrome://tracing or '
          'https://ui.perfetto.dev)' % args.timeline_path)
    return 0


if __name__ == '__main__':
    sys.exit(main())
