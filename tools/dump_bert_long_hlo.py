"""Dump the optimized HLO of the BERT-long (s2048, flash) train segment
so large-tensor traffic can be diffed against the hand-JAX ceiling
(/tmp/bert_long_hlo/ceiling.txt from tools/diff_bert_long.py).

Writes /tmp/bert_long_hlo/framework_<i>.txt and prints a tally of the
big-shape (>=256 MB) tensors appearing in each.
"""

import os
import re
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

_NBYTES = {'f32': 4, 'bf16': 2, 'f16': 2, 's32': 4, 'u32': 4,
           's64': 8, 'u8': 1, 'pred': 1}
_SHAPE = re.compile(r'(f32|bf16|f16|s32|u32|s64|u8|pred)\[([0-9,]+)\]')


def _shape_bytes(dt, dims):
    size = _NBYTES[dt]
    for d in dims.split(','):
        size *= int(d)
    return size


def big_shape_tally(path, min_mb=256):
    """Count big tensor shapes per HLO line (fusion-internal lines
    included — use entry_tally for materialized buffers).  ROOT lines
    and tuple-typed results count EVERY big element of the result
    type, not just the first match."""
    tally = Counter()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not re.match(r'(ROOT )?%?[\w.-]+ = ', line):
                continue
            rhs = line.split('=', 1)[1]
            # result type = everything before the op name '(...', which
            # for tuples spans '(shape, shape, ...)'
            head = rhs.split(') ', 1)[0] if rhs.lstrip().startswith('(') \
                else rhs.split(' ', 2)[1] if rhs.startswith(' ') else rhs
            for dt, dims in _SHAPE.findall(head):
                size = _shape_bytes(dt, dims)
                if size >= min_mb * 1024 * 1024:
                    tally['%s[%s] (%d MB)'
                          % (dt, dims, size >> 20)] += 1
    return tally


def entry_tally(path, min_mb=64):
    """Count big result buffers of top-level (ENTRY) instructions only:
    each is an actual HBM materialization in the optimized module."""
    tally = Counter()
    in_entry = False
    with open(path) as f:
        for line in f:
            if line.startswith('ENTRY'):
                in_entry = True
                continue
            if in_entry and line.startswith('}'):
                in_entry = False
            if not in_entry:
                continue
            s = line.strip()
            if not re.match(r'(ROOT )?%?[\w.-]+ = ', s):
                continue
            rhs = s.split('=', 1)[1].lstrip()
            # alias-only ops reference an existing buffer: counting
            # them (and the gtes of an already-counted tuple fusion)
            # would double-tally one materialization
            if re.search(r'\b(get-tuple-element|bitcast|parameter)\(',
                         rhs):
                continue
            if rhs.startswith('('):
                # tuple result: every element before the closing
                # ') ' — a bare ')' would cut inside the first
                # element's tiled-layout annotation 'T(8,128)...'
                head = rhs.split(') ', 1)[0]
                matches = _SHAPE.findall(head)
            else:
                # single result: ONLY the leading type token — scanning
                # further would count an operand of a scalar-result op
                # (f32[] never matches the shape regex) as a buffer
                m = _SHAPE.match(rhs)
                matches = [m.groups()] if m else []
            for dt, dims in matches:
                size = _shape_bytes(dt, dims)
                if size >= min_mb * 1024 * 1024:
                    tally['%s[%s]' % (dt, dims)] += 1
    return tally


def main():
    import jax
    from bert_long_common import build_train_segment

    parts = build_train_segment(4, 2048, fetch=())
    os.makedirs('/tmp/bert_long_hlo', exist_ok=True)
    for old in os.listdir('/tmp/bert_long_hlo'):
        # stale framework dumps from earlier runs must not be tallied
        # as this run's results (ceiling.txt is diff_bert_long's)
        if old.startswith('framework_'):
            os.unlink(os.path.join('/tmp/bert_long_hlo', old))
    compiled = jax.jit(parts['fn'], donate_argnums=(1,)).lower(
        0, parts['state'], parts['data']).compile()
    out = '/tmp/bert_long_hlo/framework_0.txt'
    with open(out, 'w') as f:
        f.write(compiled.as_text())
    print('segment 0 (%d ops) -> %s' % (len(parts['seg'].ops), out))
    ma = compiled.memory_analysis()
    if ma:
        print('  temp %d MB  output %d MB  argument %d MB'
              % (ma.temp_size_in_bytes >> 20,
                 ma.output_size_in_bytes >> 20,
                 ma.argument_size_in_bytes >> 20))

    for path in sorted(os.listdir('/tmp/bert_long_hlo')):
        full = os.path.join('/tmp/bert_long_hlo', path)
        print('\n== %s ENTRY-materialized big buffers ==' % path)
        for k, v in sorted(entry_tally(full).items(),
                           key=lambda kv: -kv[1]):
            print('  %3dx %s' % (v, k))


if __name__ == '__main__':
    main()
