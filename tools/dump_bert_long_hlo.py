"""Dump the optimized HLO of the BERT-long (s2048, flash) train segment
so large-tensor traffic can be diffed against the hand-JAX ceiling
(/tmp/bert_long_hlo/ceiling.txt from tools/diff_bert_long.py).

Writes /tmp/bert_long_hlo/framework.txt and prints a tally of the
big-shape (>=256 MB) tensors appearing in each.
"""

import os
import re
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def big_shape_tally(path, min_mb=256):
    nbytes = {'f32': 4, 'bf16': 2, 'f16': 2, 's32': 4, 'u32': 4,
              's64': 8, 'u8': 1, 'pred': 1}
    tally = Counter()
    pat = re.compile(r'(f32|bf16|f16|s32|u32|s64|u8|pred)\[([0-9,]+)\]')
    with open(path) as f:
        for line in f:
            line = line.strip()
            # count each op once by its OUTPUT shape (start of line
            # after the assignment name)
            m = re.match(r'%?[\w.-]+ = (\(?)(.*)', line)
            if not m:
                continue
            first = pat.search(line.split('=', 1)[1][:120])
            if not first:
                continue
            dt, dims = first.groups()
            size = nbytes[dt]
            for d in dims.split(','):
                size *= int(d)
            if size >= min_mb * 1024 * 1024:
                tally['%s[%s] (%d MB)' % (dt, dims, size >> 20)] += 1
    return tally


def main():
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.fluid.executor import _Segment, _make_segment_fn

    batch, seq = 4, 2048
    cfg = models.bert.BertConfig(max_pos=seq, attn_dropout=0.0)
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 42
    with fluid.program_guard(main_p, startup):
        feeds, enc, loss = models.bert.build_pretrain(cfg, seq)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Adam(1e-4), use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    batch_data = models.bert.synthetic_batch(cfg, batch, seq, rng)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        plan = exe._build_plan(main_p,
                               tuple(sorted(batch_data.keys())),
                               (loss.name,))
        os.makedirs('/tmp/bert_long_hlo', exist_ok=True)
        for i, item in enumerate(plan):
            if not isinstance(item, _Segment):
                continue
            fn = _make_segment_fn(item, item.prefer_test)
            state = {n: fluid.core.as_array(scope.find_var(n))
                     for n in item.state_names}
            data = {n: batch_data.get(
                        n, scope.find_var(n) and
                        fluid.core.as_array(scope.find_var(n)))
                    for n in item.input_names}
            compiled = jax.jit(fn, donate_argnums=(1,)).lower(
                0, state, data).compile()
            out = '/tmp/bert_long_hlo/framework_%d.txt' % i
            with open(out, 'w') as f:
                f.write(compiled.as_text())
            print('segment %d (%d ops) -> %s' % (i, len(item.ops), out))
            ma = compiled.memory_analysis()
            if ma:
                print('  temp %d MB  output %d MB  argument %d MB'
                      % (ma.temp_size_in_bytes >> 20,
                         ma.output_size_in_bytes >> 20,
                         ma.argument_size_in_bytes >> 20))

    for path in sorted(os.listdir('/tmp/bert_long_hlo')):
        full = os.path.join('/tmp/bert_long_hlo', path)
        print('\n== %s big tensors ==' % path)
        for k, v in sorted(big_shape_tally(full).items(),
                           key=lambda kv: -kv[1]):
            print('  %3dx %s' % (v, k))


if __name__ == '__main__':
    main()
