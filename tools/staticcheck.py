"""Repo-level static lint: flag hygiene + lock discipline.

Two checks, both exit-nonzero-and-name-the-line (the
check_stat_coverage.py convention), run from `make check`:

**Flags.**  Every ``FLAGS_*`` name READ anywhere in ``paddle_tpu/``
(``get_flag('FLAGS_x')``, ``get_flags([...])``, ``os.environ`` access)
must be declared in ``fluid/flags.py``'s ``_DEFAULTS`` — an undeclared
read silently returns the fallback default forever, the classic
mis-spelled-knob production bug.  And the inverse: a flag declared but
never read anywhere in the repo is dead surface (a rename that left
the old declaration behind) and is reported too.

**Locks.**  Module-level mutable registries (dicts/lists/sets assigned
at module scope) in the long-running service modules must only be
mutated under that module's module-level lock: a registry append
outside ``with _lock:`` is exactly the torn-/statusz-read bug this
repo's report trails exist to avoid.  ``monitor.py`` is the documented
exemption — its registries are GIL-disciplined by design (stats-grade
relaxed counters, see its module docstring) and carry no lock at all;
the lint asserts that stays true (adding a lock there without wiring
every site would be worse than none).

AST-based: no imports of the checked modules, so it runs in CI without
jax.  A line may opt out with a trailing ``# staticcheck: unlocked``
comment naming its reason — mutations that are init-time-only or
publish-by-rebind patterns.
"""

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, 'paddle_tpu')
FLAGS_FILE = os.path.join(PKG, 'fluid', 'flags.py')

# modules whose module-level registries must be lock-disciplined
LOCK_MODULES = [
    'paddle_tpu/fluid/serving.py',
    'paddle_tpu/fluid/health.py',
    'paddle_tpu/fluid/progcheck.py',
    'paddle_tpu/fluid/comms_plan.py',
    'paddle_tpu/fluid/elastic.py',
    'paddle_tpu/fluid/faultinject.py',
    'paddle_tpu/fluid/supervisor.py',
    'paddle_tpu/parallel/plan.py',
    'paddle_tpu/fluid/timeseries.py',
    'paddle_tpu/fluid/slo.py',
    'paddle_tpu/fluid/autopilot.py',
    'paddle_tpu/fluid/fleet.py',
    'paddle_tpu/fluid/opprof.py',
]
# documented GIL-discipline exemption: registries with NO lock at all
# (the lint fails if a lock ever appears there half-wired)
GIL_MODULES = ['paddle_tpu/fluid/monitor.py']

MUTATING_METHODS = {
    'append', 'add', 'pop', 'popitem', 'clear', 'update', 'remove',
    'discard', 'extend', 'insert', 'setdefault', '__setitem__',
}

WAIVER = re.compile(r'#\s*staticcheck:\s*unlocked')


def _py_files(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for f in filenames:
            if f.endswith('.py'):
                yield os.path.join(dirpath, f)


# ------------------------------------------------------------- flags lint

_READ_PATTERNS = (
    re.compile(r"get_flag\(\s*['\"](FLAGS_\w+)"),
    re.compile(r"environ(?:\.get)?\(\s*['\"](FLAGS_\w+)"),
    re.compile(r"environ\[\s*['\"](FLAGS_\w+)"),
    re.compile(r"getenv\(\s*['\"](FLAGS_\w+)"),
)
_GET_FLAGS_LIST = re.compile(r"get_flags\(\s*(\[[^\]]*\]|['\"]FLAGS_\w+['\"])",
                             re.S)
_FLAG_NAME = re.compile(r"FLAGS_\w+")


def declared_flags():
    """(declared flag set, compat-only flag set) from flags.py's AST."""
    with open(FLAGS_FILE) as f:
        tree = ast.parse(f.read(), FLAGS_FILE)
    declared = compat = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = [getattr(t, 'id', None) for t in node.targets]
        if '_DEFAULTS' in names:
            declared = set(
                k.value for k in node.value.keys
                if isinstance(k, ast.Constant) and
                str(k.value).startswith('FLAGS_'))
        if 'V16_COMPAT_ONLY' in names:
            compat = set(
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant))
    if declared is None:
        raise AssertionError('no _DEFAULTS dict found in flags.py')
    return declared, compat or set()


def flag_reads(paths):
    """{flag: [(file, lineno), ...]} over explicit read sites."""
    reads = {}

    def note(name, path, lineno):
        reads.setdefault(name, []).append(
            (os.path.relpath(path, ROOT), lineno))

    for path in paths:
        with open(path) as f:
            src = f.read()
        for pat in _READ_PATTERNS:
            for m in pat.finditer(src):
                note(m.group(1), path, src[:m.start()].count('\n') + 1)
        for m in _GET_FLAGS_LIST.finditer(src):
            for name in _FLAG_NAME.findall(m.group(1)):
                note(name, path, src[:m.start()].count('\n') + 1)
    return reads


def check_flags(errors):
    declared, compat = declared_flags()
    pkg_reads = flag_reads(_py_files(PKG))
    for name in sorted(pkg_reads):
        if name not in declared:
            f, ln = pkg_reads[name][0]
            errors.append(
                'FLAG UNDECLARED  %s read at %s:%d but not declared '
                'in fluid/flags.py _DEFAULTS (a typo here silently '
                'reads the fallback default forever)' % (name, f, ln))
    # reads anywhere in the repo count against dead-declaration
    # (bench.py / tools / tests legitimately read runtime flags)
    all_reads = dict(pkg_reads)
    extra = [p for p in _py_files(ROOT)
             if not p.startswith(PKG + os.sep)]
    for name, sites in flag_reads(extra).items():
        all_reads.setdefault(name, []).extend(sites)
    for name in sorted(declared):
        if name not in all_reads and name not in compat:
            errors.append(
                'FLAG NEVER READ  %s is declared in fluid/flags.py '
                'but no code reads it (dead knob or renamed read '
                'site; v1.6 compat-only knobs belong in '
                'V16_COMPAT_ONLY)' % name)
    # pallas kernel knobs must gate dispatch inside the package — a
    # FLAGS_pallas_* read only by tests/bench would pass the generic
    # dead-knob check above while the kernel library silently never
    # consults it (a dense fallback masquerading as a fused win)
    for name in sorted(declared):
        if name.startswith('FLAGS_pallas_') and name not in pkg_reads:
            errors.append(
                'FLAG PALLAS UNWIRED  %s is declared but no '
                'paddle_tpu/ code reads it — pallas dispatch knobs '
                'must be consulted by the kernel library itself, not '
                'only by tests or bench harnesses' % name)
    for name in sorted(compat):
        if name in pkg_reads:
            f, ln = pkg_reads[name][0]
            errors.append(
                'FLAG COMPAT VIOLATION  %s is declared compat-only '
                'but is read at %s:%d — move it out of '
                'V16_COMPAT_ONLY' % (name, f, ln))
    return len(declared), sum(len(v) for v in pkg_reads.values())


# -------------------------------------------------------------- lock lint

def _module_registries_and_locks(tree):
    regs, locks = set(), set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            name = getattr(t, 'id', None)
            if name is None or name.startswith('__'):
                continue   # __all__ etc. are not runtime registries
            v = node.value
            if isinstance(v, (ast.Dict, ast.List, ast.Set)) or (
                    isinstance(v, ast.Call) and
                    getattr(v.func, 'id', None) in ('dict', 'list',
                                                    'set')):
                regs.add(name)
            if isinstance(v, ast.Call):
                attr = getattr(v.func, 'attr', None)
                if attr in ('Lock', 'RLock'):
                    locks.add(name)
    return regs, locks


class _LockWalker(ast.NodeVisitor):
    """Flags mutations of module registries outside `with <lock>:`."""

    def __init__(self, regs, locks, src_lines):
        self.regs = regs
        self.locks = locks
        self.src_lines = src_lines
        self.depth = 0        # locks held (lexically)
        self.func_depth = 0
        self.found = []

    def _waived(self, node):
        line = self.src_lines[node.lineno - 1] \
            if node.lineno - 1 < len(self.src_lines) else ''
        return WAIVER.search(line) is not None

    def _is_reg(self, expr):
        return isinstance(expr, ast.Name) and expr.id in self.regs

    def _flag(self, node, what):
        if self.func_depth == 0:
            return   # import-time initialization is single-threaded
        if self.depth == 0 and not self._waived(node):
            self.found.append((node.lineno, what))

    def visit_With(self, node):
        held = any(
            isinstance(item.context_expr, ast.Call) and
            isinstance(item.context_expr.func, ast.Name) and
            item.context_expr.func.id in self.locks
            for item in node.items) or any(
            isinstance(item.context_expr, ast.Name) and
            item.context_expr.id in self.locks
            for item in node.items)
        if held:
            self.depth += 1
        self.generic_visit(node)
        if held:
            self.depth -= 1

    def visit_FunctionDef(self, node):
        self.func_depth += 1
        self.generic_visit(node)
        self.func_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and self._is_reg(f.value) and \
                f.attr in MUTATING_METHODS:
            self._flag(node, '%s.%s(...)' % (f.value.id, f.attr))
        self.generic_visit(node)

    def visit_Assign(self, node):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and self._is_reg(t.value):
                self._flag(node, '%s[...] = ...' % t.value.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        t = node.target
        if isinstance(t, ast.Subscript) and self._is_reg(t.value):
            self._flag(node, '%s[...] op= ...' % t.value.id)
        self.generic_visit(node)

    def visit_Delete(self, node):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and self._is_reg(t.value):
                self._flag(node, 'del %s[...]' % t.value.id)
        self.generic_visit(node)


def check_locks(errors):
    checked = 0
    for rel in LOCK_MODULES + GIL_MODULES:
        path = os.path.join(ROOT, rel)
        with open(path) as f:
            src = f.read()
        tree = ast.parse(src, path)
        regs, locks = _module_registries_and_locks(tree)
        if rel in GIL_MODULES:
            if locks:
                errors.append(
                    'LOCK DISCIPLINE  %s declares a module lock %s '
                    'but is the documented GIL-discipline module — '
                    'either wire every registry site through it or '
                    'remove it' % (rel, sorted(locks)))
            continue
        if regs and not locks:
            errors.append(
                'LOCK DISCIPLINE  %s has module registries %s but no '
                'module-level threading.Lock' % (rel, sorted(regs)))
            continue
        walker = _LockWalker(regs, locks, src.splitlines())
        walker.visit(tree)
        checked += len(regs)
        for lineno, what in walker.found:
            errors.append(
                'LOCK DISCIPLINE  %s:%d mutates a module registry '
                'outside its lock: %s (wrap in `with %s:` or waive '
                'with `# staticcheck: unlocked`)'
                % (rel, lineno, what, sorted(locks)[0]))
    return checked


def main():
    errors = []
    n_declared, n_reads = check_flags(errors)
    n_regs = check_locks(errors)
    if errors:
        for e in errors:
            print(e)
        print('staticcheck: %d problem(s)' % len(errors))
        return 1
    print('staticcheck: %d flags declared / %d read sites consistent; '
          '%d lock-disciplined registries clean' %
          (n_declared, n_reads, n_regs))
    return 0


if __name__ == '__main__':
    sys.exit(main())
