"""Op-cost plane gate: the attribution plane must name where a REAL
run's milliseconds go per op instance, agree with the step report, and
cost nothing when off (the fluid.opprof analog of check_memviz.py's
contract).

Runs a real LeNet training job (through Executor.warmup so the replay
snapshots ride warmed segments) with FLAGS_opprof on at snapshot
cadence 1 and the tracer live, then checks:

  1. replay: every stashed segment replays eagerly into per-instance
     rows with nonzero ms/step and output bytes, layers resolved;
  2. agreement: each segment's normalized instance costs sum to its
     measured synchronous wall, and the summed measured walls agree
     with trace.step_report()'s dispatch phase for the snapshot step
     within 10% (the acceptance band — both read the same interval);
  3. worklist: op_worklist.json is schema-valid, names >= 3 ranked
     candidates with per-instance ms/step, and cross-references the
     pallas registry (the warmed adam run must be marked covered by
     the fused_optimizer kernel);
  4. /statusz + /opprof: the op_costs section and the replay endpoint
     serve the same registry over a live status server;
  5. disabled: with FLAGS_opprof off (the default), zero snapshots are
     taken and the steady-state hot-path budgets of
     tools/check_hot_path.py must still hold.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import sys


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import tempfile
    import urllib.request
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import health, monitor, opprof, trace
    from paddle_tpu import models

    failures = []
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        feeds, pred, loss, acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(64, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (64, 1)).astype('int64')}

    fluid.set_flags({'FLAGS_opprof': True,
                     'FLAGS_opprof_snapshot_steps': 1})
    trace.enable()
    srv = health.serve(port=0)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            exe.warmup(main_p,
                       feed_shapes={'img': ((64, 1, 28, 28), 'float32'),
                                    'label': ((64, 1), 'int64')},
                       fetch_list=[loss], wait=True)
            for _ in range(3):
                exe.run(main_p, feed=feed, fetch_list=[loss])

            # 1. eager replay into per-instance rows
            done = opprof.replay_all()
            bad = {k: v for k, v in done.items()
                   if not isinstance(v, int)}
            if not done:
                failures.append('no snapshots stashed on a warmed run '
                                'with FLAGS_opprof on')
            if bad:
                failures.append('replay errors: %r' % bad)
            rep = opprof.report()
            replay_segs = [s for s in rep['segments']
                           if s['source'] == 'replay']
            if not replay_segs:
                failures.append('replay produced no registry rows')
            if not any(c['bytes_per_step'] > 0 for c in rep['top']):
                failures.append('no instance recorded output bytes')
            if not any(c.get('layer') for c in rep['top']):
                failures.append('no instance resolved a layer label '
                                '(plan-rule reuse broken)')

            # 2. normalization + step-report agreement (10% band)
            for seg in replay_segs:
                if seg['measured_ms'] is None:
                    failures.append('segment %s has no measured wall'
                                    % seg['segment'])
                    continue
                if abs(seg['attributed_ms'] - seg['measured_ms']) > \
                        1e-3 * max(seg['measured_ms'], 1e-9):
                    failures.append(
                        'segment %s instance sum %.4f != measured '
                        '%.4f ms' % (seg['segment'],
                                     seg['attributed_ms'],
                                     seg['measured_ms']))
            sr = trace.step_report()
            disp_ms = sr['steps'][-1]['phases_ms'].get('dispatch', 0.0) \
                if sr['steps'] else 0.0
            total_measured = sum(s['measured_ms'] or 0.0
                                 for s in replay_segs)
            if disp_ms <= 0:
                failures.append('step report carries no dispatch '
                                'phase on the snapshot step')
            elif abs(total_measured - disp_ms) > 0.10 * disp_ms:
                failures.append(
                    'replay walls %.4f ms vs step-report dispatch '
                    '%.4f ms: outside the 10%% agreement band'
                    % (total_measured, disp_ms))

            # 3. the worklist artifact
            wl_path = os.path.join(
                tempfile.mkdtemp(prefix='pt_opprof_'),
                'op_worklist.json')
            opprof.write_worklist(wl_path)
            with open(wl_path) as f:
                doc = json.load(f)
            cands = doc.get('candidates') or []
            if len(cands) < 3:
                failures.append('worklist names %d candidates, need '
                                '>= 3' % len(cands))
            for c in cands:
                if not (c.get('ms_per_step', 0) > 0 and c.get('ops')
                        and c.get('rank')):
                    failures.append('underspecified candidate %r' % c)
                    break
            if not any(c.get('covered_by') == 'fused_optimizer'
                       for c in cands):
                failures.append('the adam run is not cross-referenced '
                                'as covered by pallas/fused_optimizer')

            # 4. /statusz op_costs + /opprof off the live server
            with urllib.request.urlopen('%s/statusz' % srv.url,
                                        timeout=10) as resp:
                sz = json.loads(resp.read().decode('utf-8'))
            oc = sz.get('op_costs') or {}
            if not oc.get('top'):
                failures.append('/statusz op_costs has no top-K table')
            with urllib.request.urlopen('%s/opprof' % srv.url,
                                        timeout=60) as resp:
                op_doc = json.loads(resp.read().decode('utf-8'))
            if not (op_doc.get('report', {}).get('top') and
                    'worklist' in op_doc):
                failures.append('/opprof endpoint serves no '
                                'report/worklist')

        print('opprof: %d replayed segments, %d instances, dispatch '
              'agreement %.4f vs %.4f ms, %d worklist candidates'
              % (len(replay_segs), len(rep['top']), total_measured,
                 disp_ms, len(cands)))
    finally:
        health.stop()
        trace.disable()
        trace.reset()
        fluid.set_flags({'FLAGS_opprof': False,
                         'FLAGS_opprof_snapshot_steps': 16})
        opprof.reset()
        monitor.reset()

    # 5. disabled-path budgets: FLAGS_opprof off must keep the PR-2
    # hot path byte-identical (one flag read per step) and take zero
    # snapshots
    import check_hot_path
    rc = check_hot_path.main()
    if rc != 0:
        failures.append('check_hot_path budgets violated with opprof '
                        'disabled (rc=%d)' % rc)
    if monitor.counter_value('opprof/snapshots'):
        failures.append('snapshots taken with FLAGS_opprof off')

    if failures:
        for f in failures:
            print('OPPROF GATE  ' + f)
        return 1
    print('opprof: replay + agreement + worklist + statusz + disabled '
          'budgets all hold')
    return 0


if __name__ == '__main__':
    sys.exit(main())
