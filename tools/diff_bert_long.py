"""Diagnose the framework-vs-ceiling gap on long-context BERT (s2048).

Builds BOTH programs in one process, prints XLA cost analysis
(flops/bytes) for each, times them interleaved (A/B/A/B...) so tunnel
drift cannot masquerade as a framework gap, and dumps both optimized
HLOs under /tmp/bert_long_hlo/ for side-by-side inspection.

Usage: python tools/diff_bert_long.py [--steps 6] [--rounds 3]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def build_framework(batch, seq):
    import paddle_tpu.fluid as fluid
    from bert_long_common import build_bert_long_program
    main, startup, loss, batch_data = build_bert_long_program(batch, seq)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.XLAPlace(0))
    with fluid.scope_guard(scope):
        exe.run(startup)
        cost = exe.program_cost(main, batch_data, fetch_list=[loss])
        print('framework cost: %.1f GFLOP  %.2f GB/step'
              % (cost['flops'] / 1e9, cost['bytes'] / 1e9))

    def run_steps(n):
        with fluid.scope_guard(scope):
            for _ in range(n - 1):
                exe.run(main, feed=batch_data, fetch_list=[])
            out = exe.run(main, feed=batch_data, fetch_list=[loss])
            np.asarray(out[0])
    return run_steps


def build_framework_direct(batch, seq):
    """The SAME fluid program, but the compiled train segment driven in
    a bare jitted loop (state threaded by hand, donation on) — isolates
    the executor's per-step host path from the compiled program."""
    import jax
    from bert_long_common import build_train_segment
    parts = build_train_segment(batch, seq)
    fn = jax.jit(parts['fn'], donate_argnums=(1,))
    data = parts['data']
    out_state_names = parts['out_state_names']
    holder = {'state': parts['state'], 'step': 0}

    def run_steps(n):
        st = holder['state']
        for _ in range(n):
            outs = fn(holder['step'], st, data)
            holder['step'] += 1
            st = dict(st)
            st.update({k: outs[k] for k in out_state_names})
        holder['state'] = st
        smallest = min(st.values(),
                       key=lambda a: getattr(a, 'size', 1 << 60))
        np.asarray(smallest)
    return run_steps


def build_ceiling(batch, seq):
    import jax
    import jax_ceilings as jc
    # intercept run_bert's timeit to get the jitted step + state + feed
    # (run_bert only prints; we need the fn to time interleaved)
    holder = {}
    real_timeit = jc.timeit

    def capture(step, state, steps, feed):
        holder['step'] = step
        holder['state'] = jax.tree.map(jax.device_put, state)
        # device-put the feed ONCE, exactly like the real timeit —
        # storing the raw numpy here once cost every timed ceiling
        # step a ~130 KB synchronous tunnel transfer (~11 ms on this
        # rig), understating the ceiling by ~8%
        holder['feed'] = tuple(jax.device_put(np.asarray(f))
                               for f in feed)
        return 1.0  # skip run_bert's own timing loop

    jc.timeit = capture
    try:
        jc.run_bert(batch, seq, 1)
    finally:
        jc.timeit = real_timeit
    step, state, feed = holder['step'], holder['state'], holder['feed']
    lowered = step.lower(state, *feed)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    print('ceiling   cost: %.1f GFLOP  %.2f GB/step'
          % (ca.get('flops', 0) / 1e9,
             ca.get('bytes accessed', 0) / 1e9))
    os.makedirs('/tmp/bert_long_hlo', exist_ok=True)
    with open('/tmp/bert_long_hlo/ceiling.txt', 'w') as f:
        f.write(compiled.as_text())

    st = [state]

    def run_steps(n):
        for _ in range(n):
            st[0] = step(st[0], *feed)
        st[0][3].block_until_ready()  # the scalar step counter

    return run_steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=6)
    ap.add_argument('--rounds', type=int, default=3)
    ap.add_argument('--batch', type=int, default=4)
    ap.add_argument('--seq', type=int, default=2048)
    args = ap.parse_args()

    fw = build_framework(args.batch, args.seq)
    fd = build_framework_direct(args.batch, args.seq)
    ce = build_ceiling(args.batch, args.seq)
    # warm all
    fw(2)
    fd(2)
    ce(2)
    for r in range(args.rounds):
        for name, fn in (('framework', fw), ('fw-direct', fd),
                         ('ceiling  ', ce)):
            t0 = time.time()
            fn(args.steps)
            dt = (time.time() - t0) / args.steps * 1e3
            print('round %d %s: %.1f ms/step' % (r, name, dt))


if __name__ == '__main__':
    main()
