"""Diagnose the framework-vs-ceiling gap on long-context BERT (s2048).

Builds BOTH programs in one process, prints XLA cost analysis
(flops/bytes) for each, times them interleaved (A/B/A/B...) so tunnel
drift cannot masquerade as a framework gap, and dumps both optimized
HLOs under /tmp/bert_long_hlo/ for side-by-side inspection.

Usage: python tools/diff_bert_long.py [--steps 6] [--rounds 3]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def build_framework(batch, seq):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    cfg = models.bert.BertConfig(max_pos=seq, attn_dropout=0.0)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, enc, loss = models.bert.build_pretrain(cfg, seq)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Adam(1e-4), use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    import jax
    rng = np.random.RandomState(0)
    batch_data = models.bert.synthetic_batch(cfg, batch, seq, rng)
    batch_data = {k: jax.device_put(v) for k, v in batch_data.items()}
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.XLAPlace(0))
    with fluid.scope_guard(scope):
        exe.run(startup)
        cost = exe.program_cost(main, batch_data, fetch_list=[loss])
        print('framework cost: %.1f GFLOP  %.2f GB/step'
              % (cost['flops'] / 1e9, cost['bytes'] / 1e9))

    def run_steps(n):
        with fluid.scope_guard(scope):
            for _ in range(n - 1):
                exe.run(main, feed=batch_data, fetch_list=[])
            out = exe.run(main, feed=batch_data, fetch_list=[loss])
            np.asarray(out[0])
    return run_steps


def build_framework_direct(batch, seq):
    """The SAME fluid program, but the compiled train segment driven in
    a bare jitted loop (state threaded by hand, donation on) — isolates
    the executor's per-step host path from the compiled program."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.fluid.executor import _Segment, _make_segment_fn
    from paddle_tpu.fluid import core
    cfg = models.bert.BertConfig(max_pos=seq, attn_dropout=0.0)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 42
    with fluid.program_guard(main, startup):
        feeds, enc, loss = models.bert.build_pretrain(cfg, seq)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.Adam(1e-4), use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    batch_data = models.bert.synthetic_batch(cfg, batch, seq, rng)
    batch_data = {k: jax.device_put(v) for k, v in batch_data.items()}
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        plan = exe._build_plan(main, tuple(sorted(batch_data.keys())),
                               ())
        segs = [it for it in plan if isinstance(it, _Segment)]
        assert len(segs) == 1, [len(s.ops) for s in segs]
        seg = segs[0]
        fn = jax.jit(_make_segment_fn(seg), donate_argnums=(1,))
        state = {n: core.as_array(scope.find_var(n))
                 for n in seg.state_names}
        data = {n: batch_data.get(
                    n, core.as_array(scope.find_var(n)))
                for n in seg.input_names}
        out_state_names = [n for n in seg.output_names if n in state]
        holder = {'state': state, 'step': 0}

    def run_steps(n):
        st = holder['state']
        for _ in range(n):
            outs = fn(holder['step'], st, data)
            holder['step'] += 1
            st = dict(st)
            st.update({k: outs[k] for k in out_state_names})
        holder['state'] = st
        smallest = min(st.values(),
                       key=lambda a: getattr(a, 'size', 1 << 60))
        np.asarray(smallest)
    return run_steps


def build_ceiling(batch, seq):
    import jax
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax_ceilings as jc
    # replicate run_bert's setup but return a step closure + state
    # (run_bert only prints; we need the jitted fn to time interleaved)
    import jax.numpy as jnp
    V, H, L, NH, FF, TV = 30522, 768, 12, 12, 3072, 2
    rng = np.random.RandomState(0)
    ids = rng.randint(0, V, (batch, seq)).astype('int32')
    sent = np.zeros((batch, seq), 'int32')
    mlm = np.where(rng.rand(batch, seq) < 0.15,
                   rng.randint(0, V, (batch, seq)), -1).astype('int32')
    nsp = rng.randint(0, 2, (batch,)).astype('int32')
    key_bias = np.zeros((batch, seq), np.float32)

    holder = {}
    real_timeit = jc.timeit

    def capture(step, state, steps, feed):
        holder['step'] = step
        holder['state'] = jax.tree.map(jax.numpy.asarray, state)
        holder['feed'] = feed
        return 1.0  # skip run_bert's own timing loop

    jc.timeit = capture
    try:
        jc.run_bert(batch, seq, 1)
    finally:
        jc.timeit = real_timeit
    step, state, feed = holder['step'], holder['state'], holder['feed']
    lowered = step.lower(state, *feed)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    print('ceiling   cost: %.1f GFLOP  %.2f GB/step'
          % (ca.get('flops', 0) / 1e9,
             ca.get('bytes accessed', 0) / 1e9))
    os.makedirs('/tmp/bert_long_hlo', exist_ok=True)
    with open('/tmp/bert_long_hlo/ceiling.txt', 'w') as f:
        f.write(compiled.as_text())

    st = [state]

    def run_steps(n):
        for _ in range(n):
            st[0] = step(st[0], *feed)
        st[0][3].block_until_ready()  # the scalar step counter

    return run_steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--steps', type=int, default=6)
    ap.add_argument('--rounds', type=int, default=3)
    ap.add_argument('--batch', type=int, default=4)
    ap.add_argument('--seq', type=int, default=2048)
    args = ap.parse_args()

    fw = build_framework(args.batch, args.seq)
    fd = build_framework_direct(args.batch, args.seq)
    ce = build_ceiling(args.batch, args.seq)
    # warm all
    fw(2)
    fd(2)
    ce(2)
    for r in range(args.rounds):
        for name, fn in (('framework', fw), ('fw-direct', fd),
                         ('ceiling  ', ce)):
            t0 = time.time()
            fn(args.steps)
            dt = (time.time() - t0) / args.steps * 1e3
            print('round %d %s: %.1f ms/step' % (r, name, dt))


if __name__ == '__main__':
    main()
