"""Trace-plane gate: the step timeline must actually decompose a real
step, and must cost nothing when off (the fluid.trace analog of
check_hot_path.py's counter budgets).

Runs a real LeNet training step in three postures:

  1. traced, under a jax.profiler device capture: the flight recorder
     must hold spans for bind / dispatch / feed_h2d / fetch_d2h (>= 4
     distinct host phases), and the merged host+device export must be
     valid chrome-trace JSON (loadable, consistent event schema, host
     spans on their own pid next to the device events);
  2. report: step_report() phase sums must account for >= 80% of the
     traced steady step's wall time — the "where did the millisecond
     go" contract;
  3. disabled: with the tracer off (the default), the steady-state
     hot-path budgets of tools/check_hot_path.py must still hold — a
     span site that allocates or locks on the disabled path shows up
     there.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import sys

COVERAGE_MIN = float(os.environ.get('PADDLE_TPU_TRACE_COVERAGE', 0.8))
REQUIRED_PHASES = ('bind', 'dispatch', 'feed_h2d', 'fetch_d2h')


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    import tempfile
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import monitor, profiler, trace
    from paddle_tpu import models

    failures = []
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        feeds, pred, loss, acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(64, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (64, 1)).astype('int64')}

    logdir = tempfile.mkdtemp(prefix='pt_check_trace_')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        # warm up: compiles land OUTSIDE the traced window so the
        # traced step is the steady state the report must explain
        for _ in range(3):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        assert not trace.is_active(), 'tracer must default OFF'
        profiler.start_trace(logdir)
        for _ in range(3):
            l, = exe.run(main_p, feed=feed, fetch_list=[loss])
            np.asarray(l)
        profiler.stop_trace()
    assert not trace.is_active(), 'stop_trace must detach the tracer'

    # -- 1. host phases recorded ------------------------------------
    recs = trace.steps()
    if not recs:
        failures.append('no step records in the flight recorder')
    names = set()
    for r in recs:
        names.update(s[0] for s in r['spans'])
    missing = [p for p in REQUIRED_PHASES if p not in names]
    if missing:
        failures.append('host phase spans missing: %r (saw %r)'
                        % (missing, sorted(names)))
    if len(names) < 4:
        failures.append('fewer than 4 distinct host phases: %r'
                        % sorted(names))

    # -- 2. merged export is valid chrome-trace JSON -----------------
    sys.path.insert(0, os.path.join(root, 'tools'))
    import timeline
    out_path = os.path.join(logdir, 'merged_timeline.json')
    src = timeline.find_trace(logdir)
    host_path = timeline.find_host_trace(logdir)
    if host_path is None:
        failures.append('stop_trace wrote no host_trace.json')
    else:
        timeline.merge(src, host_path, out_path)
        with open(out_path) as f:
            doc = json.load(f)
        evs = doc.get('traceEvents')
        if not isinstance(evs, list) or not evs:
            failures.append('merged export has no traceEvents')
        else:
            host_evs = [e for e in evs if e.get('cat') == 'pt_host'
                        and e.get('ph') == 'X']
            dev_evs = [e for e in evs if e.get('cat') != 'pt_host']
            bad = [e for e in evs
                   if e.get('ph') == 'X' and not (
                       isinstance(e.get('name'), str) and
                       isinstance(e.get('ts'), (int, float)) and
                       isinstance(e.get('dur'), (int, float)) and
                       isinstance(e.get('pid'), int))]
            if bad:
                failures.append('%d merged events violate the '
                                'chrome-trace X schema (e.g. %r)'
                                % (len(bad), bad[0]))
            host_names = set(e['name'] for e in host_evs)
            if len(host_names) < 4:
                failures.append('merged export has < 4 distinct host '
                                'phases: %r' % sorted(host_names))
            if not dev_evs:
                failures.append('merged export lost the device events')
            host_pids = set(e['pid'] for e in host_evs)
            dev_pids = set(e.get('pid') for e in dev_evs
                           if isinstance(e.get('pid'), int))
            if host_pids & dev_pids:
                failures.append('host and device events share pids %r'
                                % (host_pids & dev_pids))
            print('merged export: %d device + %d host events, host '
                  'phases %s' % (len(dev_evs), len(host_evs),
                                 sorted(host_names)))

    # -- 3. step report explains the step --------------------------
    rep = trace.step_report()
    steady = rep['steps'][1:] if len(rep['steps']) > 1 else rep['steps']
    if not steady:
        failures.append('step_report returned no steps')
    else:
        best = max(s['coverage'] for s in steady)
        print('step report: %d steps, wall p50 %.2f ms, best steady '
              'coverage %.0f%%'
              % (rep['rollup']['count'], rep['rollup']['wall_p50_ms'],
                 100 * best))
        print(trace.format_step_report(rep))
        if best < COVERAGE_MIN:
            failures.append(
                'phase sums account for %.0f%% of step wall time '
                '(need >= %.0f%%)' % (100 * best, 100 * COVERAGE_MIN))

    trace.reset()
    monitor.reset()

    # -- 4. disabled tracer keeps the hot-path budgets ---------------
    import check_hot_path
    rc = check_hot_path.main()
    if rc != 0:
        failures.append('check_hot_path budgets violated with the '
                        'tracer disabled (rc=%d)' % rc)

    if failures:
        for f in failures:
            print('TRACE GATE  ' + f)
        return 1
    print('trace plane: ok')
    return 0


if __name__ == '__main__':
    sys.exit(main())
