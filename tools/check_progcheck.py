"""Program-verifier gate: every diagnostic class must FIRE on a
fault-injected corrupt program — by name, in a real executor run — and
the repo's model-program corpus must verify CLEAN.

Three legs (run from `make check`, CPU):

1. **Seeded defects.**  For each ``progcheck.MUTATIONS`` kind, arm the
   ``progcheck.mutate`` faultinject site, run a REAL Executor.run over
   a fresh program, and require the named diagnostic class in the
   raised ProgramVerifyError (error classes) or the verify counters
   (warning classes).  Sharding classes fire through the auto-shard
   planner path (progcheck.check_sharding on corrupt specs against a
   real mesh) since op-desc mutation cannot express them.

2. **Clean corpus.**  LeNet, BERT and GPT training programs (the
   tier-1 model set) verify with zero error-class diagnostics at
   level='full'.

3. **Disabled-path budget.**  With FLAGS_program_verify=0 the hot
   path pays nothing: tools/check_hot_path.py runs as a subprocess
   with the flag pinned off and must hold its existing budgets.
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build_mutation_program(fluid, layers, kind):
    """A program every mutation kind has an eligible site in: two
    device segments around a host op (donation hazards need a later
    consumer), a while loop (torn sub-blocks need a sub_block attr),
    and a param-reading host probe (use-after-donate needs donated
    state read downstream)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        i = layers.fill_constant([1], 'int64', 0)
        n = layers.fill_constant([1], 'int64', 2)
        cond = layers.less_than(i, n)
        wl = layers.While(cond, max_trip_count=4)
        with wl.block():
            layers.increment(i)
            layers.less_than(i, n, cond=cond)
        h = layers.fc(x, 8, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.05).minimize(loss)
        w = main.global_block().all_parameters()[0]
        probe = main.current_block().create_var(
            name='w_probe', shape=list(w.shape), dtype='float32')
        layers.py_func(lambda a: a, w, probe)
    return main, startup, loss


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, ROOT)
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import (faultinject, layers, monitor,
                                  progcheck)
    from paddle_tpu.fluid.flags import set_flags

    failures = []
    set_flags({'FLAGS_program_verify': True})

    # ---- leg 1: every mutation kind fires its class BY NAME --------
    for kind in sorted(progcheck.MUTATIONS):
        mname, cls = progcheck.MUTATIONS[kind]
        main_p, startup, loss = build_mutation_program(fluid, layers,
                                                       kind)
        c0 = monitor.counter_value('verify/diagnostics/%s' % cls)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            faultinject.configure('progcheck.mutate:mutate:%d@1'
                                  % kind)
            raised = None
            try:
                exe.run(main_p,
                        feed={'x': np.zeros((4, 8), 'float32')},
                        fetch_list=[loss])
            except progcheck.ProgramVerifyError as e:
                raised = e
            except Exception as e:   # pragma: no cover - diagnosis aid
                failures.append(
                    'kind %d (%s): wrong exception %s: %s'
                    % (kind, mname, type(e).__name__, e))
                faultinject.reset()
                continue
            finally:
                faultinject.reset()
        c1 = monitor.counter_value('verify/diagnostics/%s' % cls)
        if cls in progcheck.ERROR_CLASSES:
            if raised is None:
                failures.append(
                    'kind %d (%s): executor run did NOT raise '
                    'ProgramVerifyError' % (kind, mname))
            elif cls not in str(raised):
                failures.append(
                    'kind %d (%s): error does not name class %s: %s'
                    % (kind, mname, cls, str(raised)[:200]))
        else:
            if raised is not None:
                failures.append(
                    'kind %d (%s): warning class %s raised: %s'
                    % (kind, mname, cls, str(raised)[:200]))
            if c1 <= c0:
                failures.append(
                    'kind %d (%s): verify/diagnostics/%s did not '
                    'count (%g -> %g)' % (kind, mname, cls, c0, c1))
        if faultinject.fired('progcheck.mutate') != 0:
            failures.append('kind %d: faultinject.reset left state'
                            % kind)
        print('defect kind %d %-15s -> %-18s %s'
              % (kind, mname, cls,
                 'RAISED' if raised is not None else 'counted'))

    # ---- leg 1b: sharding classes through the planner path ---------
    from jax.sharding import PartitionSpec as P
    shard_cases = [
        ('shard_unknown_axis', {'w': P('bogus_axis')}),
        ('shard_indivisible', {'w': P('dp')}),
        ('shard_conflict', {'w': P('dp', 'dp')}),
    ]
    for cls, specs in shard_cases:
        try:
            progcheck.check_sharding({'w': (6, 6)}, specs,
                                     {'dp': 4, 'mp': 2},
                                     origin='check_progcheck')
            failures.append('%s: check_sharding did not raise' % cls)
        except progcheck.ProgramVerifyError as e:
            if cls not in str(e):
                failures.append('%s: error does not name the class: %s'
                                % (cls, str(e)[:200]))
            print('defect shard %-22s -> RAISED' % cls)

    # ---- leg 2: the model corpus verifies clean --------------------
    from paddle_tpu.models import bert, gpt, lenet
    corpus = []
    m, s = fluid.Program(), fluid.Program()
    with fluid.program_guard(m, s):
        feeds, _pred, loss, _acc = lenet.build()
        fluid.optimizer.SGD(0.05).minimize(loss)
    corpus.append(('lenet', m, s, tuple(feeds), loss))
    cfg = bert.BertConfig(vocab_size=256, hidden=32, layers=1, heads=2)
    m, s = fluid.Program(), fluid.Program()
    with fluid.program_guard(m, s):
        feeds, _enc, loss = bert.build_pretrain(cfg, seq_len=8)
        fluid.optimizer.Adam(1e-4).minimize(loss)
    corpus.append(('bert', m, s, tuple(feeds), loss))
    gcfg = gpt.GptConfig(vocab_size=256, hidden=32, layers=1, heads=2)
    m, s = fluid.Program(), fluid.Program()
    with fluid.program_guard(m, s):
        feeds, _logits, loss = gpt.build_lm(gcfg, seq_len=8)
        fluid.optimizer.Adam(1e-4).minimize(loss)
    corpus.append(('gpt', m, s, tuple(feeds), loss))
    for name, main_p, startup, feed_names, loss in corpus:
        rep = progcheck.verify_program(
            main_p, feed_names=feed_names, fetch_names=(loss.name,),
            level='full', startup_program=startup,
            raise_on_error=False)
        if not rep.ok():
            failures.append('%s program has verifier errors: %s'
                            % (name, [d.format().splitlines()[0]
                                      for d in rep.errors[:4]]))
        srep = progcheck.verify_program(startup, level='full',
                                        raise_on_error=False)
        if not srep.ok():
            failures.append('%s STARTUP program has errors: %s'
                            % (name, [d.format().splitlines()[0]
                                      for d in srep.errors[:4]]))
        print('corpus %-6s ok=%s ops=%d shape-checked=%d (%s)'
              % (name, rep.ok(), rep.ops_checked, rep.shape_checked,
                 ', '.join('%s=%d' % kv
                           for kv in sorted(rep.counts().items()))
                 or 'clean'))

    # ---- leg 2b: /statusz verify section is populated --------------
    from paddle_tpu.fluid import health
    sz = health.statusz()
    v = sz.get('verify')
    if not v or not v.get('counters', {}).get('programs'):
        failures.append('/statusz verify section missing or empty: %r'
                        % (v,))
    elif not v.get('reports'):
        failures.append('/statusz verify report trail is empty')
    else:
        print('/statusz verify: %d programs, %d report(s) on the '
              'trail' % (v['counters']['programs'],
                         len(v['reports'])))

    # ---- leg 3: disabled path holds the hot-path budgets -----------
    set_flags({'FLAGS_program_verify': False})
    env = dict(os.environ, FLAGS_program_verify='0',
               JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools',
                                      'check_hot_path.py')],
        env=env, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        failures.append('check_hot_path with FLAGS_program_verify=0 '
                        'failed:\n%s%s' % (r.stdout[-1500:],
                                           r.stderr[-1500:]))
    else:
        print('disabled path: ' + r.stdout.strip().splitlines()[-1])

    if failures:
        for f in failures:
            print('PROGCHECK GATE FAILURE  ' + f)
        return 1
    print('progcheck gate: %d defect classes fire by name, corpus '
          'clean, disabled path within budgets'
          % (len(progcheck.MUTATIONS) + len(shard_cases)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
