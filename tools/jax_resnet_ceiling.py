"""Pure-JAX ResNet-50 bf16 train step: the PLATFORM CEILING for the
bench config (b128, NHWC, momentum) — what a hand-tuned JAX user would
write with no framework in the loop.  Run `python
tools/jax_resnet_ceiling.py [batch]` on the same chip as bench.py and
compare: the gap between the two is the framework's overhead.

Measured 2026-07 on the attached v5e-class chip: 2543 img/s b128
(50.3 ms/step) vs bench.py's 2506 img/s — the fluid-compatible path is
within 1.5% of hand-written JAX; see BENCHMARKS.md.

NOTE the synchronization style: on this remote-attached device a value
fetch (np.asarray) is the reliable sync; block_until_ready alone
returns early and times dispatch, not compute.
"""
import sys, time, json
import numpy as np
import jax
import jax.numpy as jnp

def conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), 'SAME',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))

def bn(x, p, name):
    g, b = p[name + '_g'], p[name + '_b']
    xf = x.astype(jnp.float32)
    cnt = x.shape[0] * x.shape[1] * x.shape[2]
    s1 = jnp.sum(xf, (0, 1, 2))
    s2 = jnp.sum(xf * xf, (0, 1, 2))
    m = s1 / cnt
    v = jnp.maximum(s2 / cnt - m * m, 0.)
    y = (xf - m) * jax.lax.rsqrt(v + 1e-5) * g + b
    return y.astype(x.dtype)

def block(x, p, pre, cin, cmid, stride):
    h = jax.nn.relu(bn(conv(x, p[pre + 'c1'], 1), p, pre + 'b1'))
    h = jax.nn.relu(bn(conv(h, p[pre + 'c2'], stride), p, pre + 'b2'))
    h = bn(conv(h, p[pre + 'c3'], 1), p, pre + 'b3')
    if stride != 1 or cin != cmid * 4:
        x = bn(conv(x, p[pre + 'cs'], stride), p, pre + 'bs')
    return jax.nn.relu(x + h)

CFG = [(3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2)]

def init_params(rng):
    p = {}
    def cw(name, kh, kw, ci, co):
        p[name] = (rng.randn(kh, kw, ci, co) *
                   (2.0 / (kh * kw * ci)) ** 0.5).astype(np.float32)
    def bnp(name, c):
        p[name + '_g'] = np.ones(c, np.float32)
        p[name + '_b'] = np.zeros(c, np.float32)
    cw('stem', 7, 7, 3, 64); bnp('stem_bn', 64)
    cin = 64
    for gi, (n, cmid, stride) in enumerate(CFG):
        for bi in range(n):
            pre = 'g%db%d' % (gi, bi)
            st = stride if bi == 0 else 1
            cw(pre + 'c1', 1, 1, cin, cmid); bnp(pre + 'b1', cmid)
            cw(pre + 'c2', 3, 3, cmid, cmid); bnp(pre + 'b2', cmid)
            cw(pre + 'c3', 1, 1, cmid, cmid * 4); bnp(pre + 'b3', cmid * 4)
            if st != 1 or cin != cmid * 4:
                cw(pre + 'cs', 1, 1, cin, cmid * 4); bnp(pre + 'bs', cmid * 4)
            cin = cmid * 4
    p['fc_w'] = (rng.randn(2048, 1000) * 0.01).astype(np.float32)
    p['fc_b'] = np.zeros(1000, np.float32)
    return p

def forward(p, x):
    x = x.astype(jnp.bfloat16)
    pb = {k: (v.astype(jnp.bfloat16) if v.ndim == 4 else v)
          for k, v in p.items()}
    h = jax.nn.relu(bn(conv(x, pb['stem'], 2), pb, 'stem_bn'))
    h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), 'SAME')
    cin = 64
    for gi, (n, cmid, stride) in enumerate(CFG):
        for bi in range(n):
            st = stride if bi == 0 else 1
            h = block(h, pb, 'g%db%d' % (gi, bi), cin, cmid, st)
            cin = cmid * 4
    h = jnp.mean(h.astype(jnp.float32), (1, 2))
    return h @ p['fc_w'] + p['fc_b']

def loss_fn(p, x, y):
    logits = forward(p, x)
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.mean(jnp.take_along_axis(logp, y, 1))

@jax.jit
def step(p, mom, x, y):
    l, g = jax.value_and_grad(loss_fn)(p, x, y)
    mom = jax.tree.map(lambda m, gg: 0.9 * m + gg, mom, g)
    p = jax.tree.map(lambda w, m: w - 0.1 * m, p, mom)
    return l, p, mom

def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    rng = np.random.RandomState(0)
    p = init_params(rng)
    mom = jax.tree.map(np.zeros_like, p)
    x = jax.device_put(rng.rand(batch, 224, 224, 3).astype('float32'))
    y = jax.device_put(rng.randint(0, 1000, (batch, 1)))
    l, p2, mom2 = step(p, mom, x, y)
    print('warm loss', float(np.asarray(l)))
    for _ in range(4):
        l, p2, mom2 = step(p2, mom2, x, y)
    np.asarray(l)
    steps = 30
    t0 = time.time()
    for _ in range(steps):
        l, p2, mom2 = step(p2, mom2, x, y)
    lv = float(np.asarray(l))  # value fetch = real synchronization
    dt = time.time() - t0
    print('final loss', lv)
    print(json.dumps({'pure_jax_img_per_sec': round(batch * steps / dt, 1),
                      'ms_per_step': round(dt / steps * 1000, 2)}))

main()
