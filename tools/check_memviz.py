"""Memory-plane gate: the device-memory observability plane must
attribute, sample and export on a REAL run, and cost nothing when off
(the fluid.memviz analog of check_trace.py's contract).

Runs a real LeNet training job (through Executor.warmup so the AOT
plane — where attribution rides — is engaged) with FLAGS_memviz on and
the tracer live, then checks:

  1. attribution: per-(program, segment) rows with named top buffers
     land in memviz.report(), classes + overhead sum back to the
     executable's memory_analysis() argument arena;
  2. sampler: every memviz/live_bytes/<class> gauge is populated and
     param bytes are nonzero (LeNet's conv/fc weights are resident);
  3. /statusz: the memory section carries the top-K attribution table
     (not just the four scalars) off a live status server;
  4. counter track: the flight-recorder dump holds schema-valid
     Perfetto 'C' events for memviz/live_bytes on the same clock as
     the step spans (the tools/timeline.py merge input);
  5. disabled: with FLAGS_memviz off (the default), the steady-state
     hot-path budgets of tools/check_hot_path.py must still hold.

Run from `make check` (CPU: JAX_PLATFORMS=cpu).
"""

import json
import os
import sys


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import tempfile
    import urllib.request
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import health, memviz, monitor, trace
    from paddle_tpu import models

    failures = []
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        feeds, pred, loss, acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(64, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (64, 1)).astype('int64')}

    fluid.set_flags({'FLAGS_memviz': True})
    trace.enable()
    srv = health.serve(port=0)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            exe.warmup(main_p,
                       feed_shapes={'img': ((64, 1, 28, 28), 'float32'),
                                    'label': ((64, 1), 'int64')},
                       fetch_list=[loss], wait=True)
            for _ in range(4):
                exe.run(main_p, feed=feed, fetch_list=[loss])

        # 1. attribution rows with named contributors, summing honest
        rows = memviz.report()
        if not rows:
            failures.append('no attribution rows after a warmed run')
        for r in rows:
            named = sum(r['classes'].values())
            if abs(named + r['arg_overhead_bytes'] -
                   r['argument_bytes']) > 1.0:
                failures.append(
                    'segment %s/%s classes %r + overhead %g != '
                    'argument arena %g'
                    % (r['program'], r['segment'], r['classes'],
                       r['arg_overhead_bytes'], r['argument_bytes']))
        if rows and not any(r['top_buffers'] for r in rows):
            failures.append('attribution rows name no buffers')

        # 2. per-class live gauges
        for cls in ('param', 'state', 'feed', 'exec', 'other'):
            if monitor.gauge_value('memviz/live_bytes/%s' % cls,
                                   None) is None:
                failures.append('gauge memviz/live_bytes/%s never '
                                'published' % cls)
        if not monitor.gauge_value('memviz/live_bytes/param'):
            failures.append('LeNet params not attributed in the '
                            'live census')
        if not monitor.counter_value('memviz/samples'):
            failures.append('sampler never ran with FLAGS_memviz on')

        # 3. /statusz memory table off the live server
        with urllib.request.urlopen('%s/statusz' % srv.url,
                                    timeout=10) as resp:
            sz = json.loads(resp.read().decode('utf-8'))
        mem = sz.get('memory') or {}
        if not mem.get('attribution'):
            failures.append('/statusz memory section has no '
                            'attribution table')
        elif not mem['attribution'][0].get('top_buffers'):
            failures.append('/statusz attribution rows have no named '
                            'top buffers')

        # 4. counter track in the dump (the timeline-merge input)
        dump_path = os.path.join(tempfile.mkdtemp(prefix='pt_memviz_'),
                                 'dump.json')
        trace.dump(dump_path)
        with open(dump_path) as f:
            doc = json.load(f)
        cs = [e for e in doc['traceEvents'] if e.get('ph') == 'C']
        xs = [e for e in doc['traceEvents'] if e.get('ph') == 'X']
        if not cs:
            failures.append('no counter-track events in the dump')
        for e in cs:
            if e.get('name') != 'memviz/live_bytes' or \
                    not isinstance(e.get('ts'), (int, float)) or \
                    not isinstance(e.get('args'), dict):
                failures.append('malformed counter event %r' % (e,))
                break
        if cs and xs:
            ts = [e['ts'] for e in xs
                  if isinstance(e.get('ts'), (int, float))]
            lo, hi = min(ts), max(ts) + 1e6
            if not all(lo <= e['ts'] <= hi for e in cs):
                failures.append('counter samples not on the span '
                                'clock')
        print('memviz: %d attribution rows, %d counter samples, live '
              'param bytes %s, statusz table rows %d'
              % (len(rows), len(cs),
                 int(monitor.gauge_value('memviz/live_bytes/param')),
                 len(mem.get('attribution') or [])))
    finally:
        health.stop()
        trace.disable()
        trace.reset()
        fluid.set_flags({'FLAGS_memviz': False})
        memviz.reset()
        monitor.reset()

    # 5. disabled-path budgets: FLAGS_memviz off must keep the PR-2
    # hot path byte-identical (one flag read per step)
    import check_hot_path
    rc = check_hot_path.main()
    if rc != 0:
        failures.append('check_hot_path budgets violated with memviz '
                        'disabled (rc=%d)' % rc)

    if failures:
        for f in failures:
            print('MEMVIZ GATE  ' + f)
        return 1
    print('memviz: attribution + sampler + statusz + counter track + '
          'disabled budgets all hold')
    return 0


if __name__ == '__main__':
    sys.exit(main())
