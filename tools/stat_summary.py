"""Render or diff fluid.monitor JSONL dumps, or a fluid.trace step
report.

Usage:
  python tools/stat_summary.py run.jsonl            # render last line
  python tools/stat_summary.py before.jsonl after.jsonl   # diff
  python tools/stat_summary.py --live               # snapshot of THIS
                                                    # process's registry
  python tools/stat_summary.py --steps dump.json    # per-step phase
                                                    # report from a
                                                    # trace.dump() file
  python tools/stat_summary.py --steps job.json --rank 1
                                  # one rank's steps out of a merged
                                  # job dump (trace.collect_job /
                                  # tools/timeline.py --job output)
  python tools/stat_summary.py --plan run.jsonl     # collective-
                                  # planner rollup: arm mix, wire vs
                                  # dense-equivalent bytes, cost-model
                                  # predicted vs measured
  python tools/stat_summary.py --memory run.jsonl   # device-memory
                                  # rollup: live HBM by class, high
                                  # watermark, budget utilization,
                                  # per-program peaks, OOM/watermark
                                  # incident counts (fluid.memviz)
  python tools/stat_summary.py --autoshard run.jsonl
                                  # auto-sharding planner rollup:
                                  # chosen dp/fsdp/tp layout, plan
                                  # builds/reuse, candidates priced,
                                  # HBM-gate rejections, unpriced
                                  # terms (parallel/plan.py)
  python tools/stat_summary.py --ops run.jsonl     # op-cost plane
                                  # rollup: snapshots taken, eager
                                  # replays, attributed vs honest
                                  # unattributed ms, capture events
                                  # consumed/dropped, worklist size
                                  # (fluid.opprof)
  python tools/stat_summary.py --verify run.jsonl
                                  # static-verifier rollup: programs
                                  # checked/clean, diagnostics by
                                  # class, seeded chaos mutations,
                                  # verify wall time
                                  # (fluid.progcheck)
  python tools/stat_summary.py --watch 2 http://host:port/metrics.json
  python tools/stat_summary.py --watch 2 run.jsonl [--iterations K]
                                  # LIVE mode: re-poll the source
                                  # every N seconds and render each
                                  # series' trend — reset-aware rates
                                  # for counters, levels for gauges,
                                  # windowed mean for histograms,
                                  # sparklines — via the
                                  # fluid.timeseries window math

One-file mode prints the last record as a sorted table (counters,
gauges, histogram sum/count).  Two-file mode prints after-minus-before
for counters and histograms — the per-interval rates a trajectory of
dump_jsonl() lines is for (e.g. diffing two BENCH rounds' monitor
sections).  --steps reads the flight-recorder dump fluid.trace.dump()
writes (its 'ptSteps' records) and prints the bind / feed_h2d /
dispatch / fetch_d2h breakdown per step with p50/p99/slowest rollups.
Companion of tools/timeline.py (traces) and the profiler table: this
one reads the ALWAYS-ON stats.
"""

import json
import os
import sys


def load_last(path):
    """Last JSONL record of `path` (one dump_jsonl line per step)."""
    last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                last = json.loads(line)
    if last is None:
        raise ValueError('no records in %s' % path)
    return last


def _rows(rec):
    rows = []
    for n, v in sorted(rec.get('counters', {}).items()):
        rows.append((n, 'counter', v))
    for n, v in sorted(rec.get('gauges', {}).items()):
        rows.append((n, 'gauge', v))
    for n, h in sorted(rec.get('histograms', {}).items()):
        rows.append((n + '/count', 'histogram', float(h['count'])))
        rows.append((n + '/sum', 'histogram', h['sum']))
    return rows


def _fmt(v):
    if v == int(v) and abs(v) < 1e15:
        return '%d' % int(v)
    return '%.6g' % v


def render(rec, out=None):
    out = out if out is not None else sys.stdout
    out.write('%-52s %-10s %14s\n' % ('stat', 'kind', 'value'))
    for n, kind, v in _rows(rec):
        out.write('%-52s %-10s %14s\n' % (n, kind, _fmt(v)))


def diff(before, after, out=None):
    """after − before for cumulative stats; gauges show both levels."""
    out = out if out is not None else sys.stdout
    b = dict((n, v) for n, k, v in _rows(before) if k != 'gauge')
    out.write('%-52s %14s\n' % ('stat', 'delta'))
    for n, kind, v in _rows(after):
        if kind == 'gauge':
            continue
        out.write('%-52s %14s\n' % (n, _fmt(v - b.get(n, 0.0))))
    ga = after.get('gauges', {})
    gb = before.get('gauges', {})
    for n in sorted(set(ga) | set(gb)):
        out.write('%-52s %14s -> %s\n'
                  % (n + ' (gauge)', _fmt(gb.get(n, 0.0)),
                     _fmt(ga.get(n, 0.0))))


def steps_report(path, out=None, rank=None):
    """Per-step phase table from a fluid.trace.dump() file; `rank`
    filters a merged job dump (trace.collect_job tags each record with
    its worker rank) down to one worker's steps."""
    # resolve stdout at CALL time: the module may be imported while a
    # test harness has stdout captured, and a def-time default would
    # pin that (soon-closed) stream
    out = out if out is not None else sys.stdout
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.fluid import trace as pt_trace
    with open(path) as f:
        recs = json.load(f).get('ptSteps', [])
    if rank is not None:
        ranks = sorted({str(r.get('rank')) for r in recs
                        if r.get('rank') is not None})
        recs = [r for r in recs if str(r.get('rank')) == str(rank)]
        if not recs:
            out.write('no step records for rank %s in %s (ranks '
                      'present: %s)\n'
                      % (rank, path, ', '.join(ranks) or 'none'))
            return 1
        out.write('rank %s:\n' % rank)
    if not recs:
        out.write('no step records in %s (was the tracer enabled?)\n'
                  % path)
        return 1
    rep = pt_trace.report_from_records(recs)
    out.write(pt_trace.format_step_report(rep) + '\n')
    return 0


def plan_report(rec, out=None):
    """Collective-planner rollup from one monitor record: which arms
    ran (comms/plan_arm/*), the wire bytes the plan moved vs what flat
    dense would have (the measured saving), and the cost model's
    predicted-vs-measured seconds.  The same numbers /statusz's
    comms_plan section serves live."""
    out = out if out is not None else sys.stdout
    c = rec.get('counters', {})
    arms = {n.rsplit('/', 1)[1]: v for n, v in c.items()
            if n.startswith('comms/plan_arm/')}
    if not arms:
        out.write('no comms/plan_arm/* counters: the collective '
                  'planner never ran in this record\n')
        return 1
    total = sum(arms.values())
    out.write('collective planner rollup\n')
    for arm in sorted(arms):
        out.write('  arm %-8s %10d dispatches (%.0f%%)\n'
                  % (arm, arms[arm], 100.0 * arms[arm] / total))
    wire = c.get('comms/plan_wire_bytes', 0.0)
    dense = c.get('comms/plan_dense_equiv_bytes', 0.0)
    if dense > 0:
        out.write('  wire bytes      %14s vs dense-equiv %s '
                  '(%.2fx reduction)\n'
                  % (_fmt(wire), _fmt(dense),
                     dense / wire if wire > 0 else float('inf')))
    fused = c.get('comms/plan_fused_grads', 0.0)
    if fused:
        out.write('  fused grads     %14s\n' % _fmt(fused))
    pred = c.get('comms/plan_predicted_seconds', 0.0)
    meas = c.get('comms/plan_measured_seconds', 0.0)
    if meas > 0:
        out.write('  cost model      predicted %.6gs vs measured '
                  '%.6gs (ratio %.2f)\n' % (pred, meas, pred / meas))
    return 0


def autoshard_report(rec, out=None):
    """Auto-sharding planner rollup from one monitor record: the
    chosen (dp, fsdp, tp) layout gauges, plan build/reuse volume, the
    candidate table size, HBM-gate rejections and the unpriced-term
    honesty counter — the offline form of /statusz's auto_shard
    section."""
    out = out if out is not None else sys.stdout
    c = rec.get('counters', {})
    g = rec.get('gauges', {})
    builds = c.get('parallel/plan_builds', 0.0)
    if not builds:
        out.write('no parallel/plan_* counters: the auto-sharding '
                  'planner never ran in this record '
                  '(FLAGS_auto_shard)\n')
        return 1
    out.write('auto-sharding planner rollup\n')
    out.write('  layout          dp=%d fsdp=%d tp=%d\n'
              % (g.get('parallel/plan_layout_dp', 0),
                 g.get('parallel/plan_layout_fsdp', 0),
                 g.get('parallel/plan_layout_tp', 0)))
    out.write('  plan builds     %10d (reused %d)\n'
              % (builds, c.get('parallel/plan_reused', 0.0)))
    out.write('  candidates      %10d priced\n'
              % c.get('parallel/plan_candidates', 0.0))
    rej = c.get('parallel/plan_hbm_rejected', 0.0)
    if rej:
        out.write('  HBM gate        %10d layouts rejected before '
                  'compile\n' % rej)
    unpriced = c.get('parallel/plan_unpriced', 0.0)
    if unpriced:
        out.write('  unpriced terms  %10d (no comms_model.json '
                  'entry: heuristic byte pricing)\n' % unpriced)
    out.write('  params          %10d sharded, %d replicated\n'
              % (c.get('parallel/plan_params_sharded', 0.0),
                 c.get('parallel/plan_params_replicated', 0.0)))
    return 0


def _fmt_bytes(b):
    b = float(b)
    if b >= 1 << 30:
        return '%.2fGiB' % (b / (1 << 30))
    if b >= 1 << 20:
        return '%.1fMiB' % (b / (1 << 20))
    if b >= 1024:
        return '%.1fKiB' % (b / 1024.0)
    return '%dB' % int(b)


def memory_report(rec, out=None):
    """Device-memory rollup from one monitor record: the memviz
    live-HBM classes, high watermark, budget utilization, per-program
    attributed peaks and incident counters — the offline form of the
    /statusz memory section."""
    out = out if out is not None else sys.stdout
    g = rec.get('gauges', {})
    c = rec.get('counters', {})
    total = g.get('memviz/live_bytes_total')
    if total is None and not any(n.startswith('memviz/')
                                 for n in list(g) + list(c)):
        out.write('no memviz/* stats in this record: enable '
                  'FLAGS_memviz for the live-HBM sampler\n')
        return 1
    out.write('device-memory rollup (fluid.memviz)\n')
    if total is not None:
        classes = {n.rsplit('/', 1)[1]: v for n, v in g.items()
                   if n.startswith('memviz/live_bytes/')}
        out.write('  live HBM        %12s across %d arrays (%s)\n'
                  % (_fmt_bytes(total),
                     int(g.get('memviz/live_arrays', 0)),
                     ', '.join('%s=%s' % (k, _fmt_bytes(classes[k]))
                               for k in sorted(classes))))
        hwm = g.get('memviz/live_bytes_hwm')
        if hwm is not None:
            out.write('  high watermark  %12s\n' % _fmt_bytes(hwm))
        util = g.get('memviz/budget_utilization')
        if util is not None:
            out.write('  budget          %11.1f%% utilized\n'
                      % (100.0 * util))
    peaks = sorted(((n.rsplit('/', 1)[1], v) for n, v in g.items()
                    if n.startswith('memviz/program_peak_bytes/')),
                   key=lambda kv: -kv[1])
    for prog, peak in peaks[:8]:
        out.write('  program %-12s peak %12s\n'
                  % (prog, _fmt_bytes(peak)))
    for name, label in (('memviz/samples', 'census samples'),
                        ('memviz/segments_attributed',
                         'segments attributed'),
                        ('memviz/watermark_trips', 'watermark trips'),
                        ('memviz/spike_trips', 'spike trips'),
                        ('memviz/oom_incidents', 'OOM incidents'),
                        ('memviz/oom_dumps', 'OOM dumps'),
                        ('memviz/analysis_unavailable',
                         'analysis unavailable')):
        v = c.get(name)
        if v:
            out.write('  %-22s %10d\n' % (label, v))
    return 0


def ops_report(rec, out=None):
    """Op-cost attribution rollup from one monitor record: snapshot /
    replay volume, the attributed-vs-unattributed ms split, capture
    event consumption (and the dropped-row honesty counter), and the
    ranked-worklist size gauge — the offline form of /statusz's
    op_costs section (fluid.opprof)."""
    out = out if out is not None else sys.stdout
    c = rec.get('counters', {})
    g = rec.get('gauges', {})
    if not any(n.startswith('opprof/') for n in list(c) + list(g)):
        out.write('no opprof/* stats in this record: enable '
                  'FLAGS_opprof for the op-cost attribution plane\n')
        return 1
    out.write('op-cost attribution rollup (fluid.opprof)\n')
    for name, label in (('opprof/snapshots', 'segment snapshots'),
                        ('opprof/replays', 'eager replays'),
                        ('opprof/capture_events',
                         'capture events consumed'),
                        ('opprof/dropped_events',
                         'malformed events dropped')):
        v = c.get(name)
        if v:
            out.write('  %-26s %10d\n' % (label, v))
    att = g.get('opprof/attributed_ms_total')
    unatt = g.get('opprof/unattributed_ms_total')
    if att is not None:
        total = att + (unatt or 0.0)
        out.write('  attributed ms/step         %10.4f (%.1f%% of '
                  'observed)\n'
                  % (att, 100.0 * att / total if total else 100.0))
    if unatt:
        out.write('  unattributed ms/step       %10.4f\n' % unatt)
    inst = g.get('opprof/instances')
    if inst is not None:
        out.write('  op instances tracked       %10d\n' % inst)
    wl = g.get('opprof/worklist_candidates')
    if wl is not None:
        out.write('  kernel-worklist candidates %10d\n' % wl)
    prof_drop = c.get('profiler/dropped_events')
    if prof_drop:
        out.write('  profiler rows dropped      %10d (malformed '
                  'device events)\n' % prof_drop)
    return 0


def verify_report(rec, out=None):
    """Static-verifier rollup from one monitor record: programs
    checked vs clean, error/warning volume, the per-diagnostic-class
    breakdown (sorted loudest first), seeded chaos mutations, and the
    verification wall-time histogram — the offline form of /statusz's
    verify section (fluid.progcheck)."""
    out = out if out is not None else sys.stdout
    c = rec.get('counters', {})
    h = rec.get('histograms', {})
    programs = c.get('verify/programs', 0.0)
    if not programs:
        out.write('no verify/* counters: the static verifier never '
                  'ran in this record (FLAGS_program_verify, '
                  'Executor.warmup, or a transpiler output)\n')
        return 1
    out.write('program-verifier rollup\n')
    out.write('  programs checked %9d (%d fully clean)\n'
              % (programs, c.get('verify/clean', 0.0)))
    out.write('  errors           %9d\n' % c.get('verify/errors', 0.0))
    out.write('  warnings         %9d\n'
              % c.get('verify/warnings', 0.0))
    prefix = 'verify/diagnostics/'
    by_class = sorted(((k[len(prefix):], v) for k, v in c.items()
                       if k.startswith(prefix)),
                      key=lambda kv: -kv[1])
    for cls, n in by_class:
        out.write('    %-22s %8d\n' % (cls, n))
    mut = c.get('verify/mutations', 0.0)
    if mut:
        out.write('  seeded mutations %9d (faultinject '
                  'progcheck.mutate)\n' % mut)
    vs = h.get('verify/seconds')
    if vs and vs.get('count'):
        out.write('  verify wall      %9.1f ms mean over %d runs\n'
                  % (1e3 * vs['sum'] / vs['count'], vs['count']))
    return 0


def _poll_source(source):
    """One sample of `source` -> (now, counters, gauges, hists) where
    hists is {name: (count, sum, edges, counts)} (edges/counts None
    when the source only records the count/sum rollup).  The source is
    a /metrics.json URL (live scrape) or a dump_jsonl trajectory file
    (newest line of a growing file)."""
    import time
    if source.startswith('http://') or source.startswith('https://'):
        import urllib.request
        with urllib.request.urlopen(source, timeout=10) as resp:
            doc = json.loads(resp.read())
        state = doc.get('state', doc)
        hists = {n: (h.get('count', 0), h.get('sum', 0.0),
                     h.get('edges'), h.get('counts'))
                 for n, h in (state.get('hists') or {}).items()}
        return (time.time(), dict(state.get('counters') or {}),
                dict(state.get('gauges') or {}), hists)
    rec = load_last(source)
    hists = {n: (h.get('count', 0), h.get('sum', 0.0), None, None)
             for n, h in (rec.get('histograms') or {}).items()}
    return (rec.get('ts', time.time()),
            dict(rec.get('counters') or {}),
            dict(rec.get('gauges') or {}), hists)


def watch(interval, source, iterations=None, out=None):
    """Live trend view: poll `source` every `interval` seconds,
    accumulate (ts, step, value) points per series, and render rates /
    levels / windowed means with sparklines — all derived through
    fluid.timeseries' window math on plain point lists, the same code
    the /timeseries endpoint runs on the in-process rings."""
    out = out if out is not None else sys.stdout
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import time
    from paddle_tpu.fluid import timeseries as ts
    keep = 256
    series = {}   # name -> {'kind': ..., 'points': [...], 'edges': e}
    tick = 0
    while iterations is None or tick < iterations:
        if tick:
            time.sleep(interval)
        tick += 1
        try:
            now, counters, gauges, hists = _poll_source(source)
        except Exception as e:
            out.write('watch: poll of %s failed: %s\n' % (source, e))
            continue
        for n, v in counters.items():
            s = series.setdefault(n, {'kind': 'counter', 'points': []})
            s['points'] = (s['points'] + [(now, None, float(v))])[-keep:]
        for n, v in gauges.items():
            s = series.setdefault(n, {'kind': 'gauge', 'points': []})
            s['points'] = (s['points'] + [(now, None, float(v))])[-keep:]
        for n, (cnt, total, edges, counts) in hists.items():
            s = series.setdefault(n, {'kind': 'hist', 'points': [],
                                      'edges': edges})
            s['edges'] = edges or s.get('edges')
            s['points'] = (s['points'] +
                           [(now, None, int(cnt), float(total),
                             tuple(counts or ()))])[-keep:]
        out.write('\n-- watch tick %d  %s  (%d series, %gs interval)\n'
                  % (tick, time.strftime('%H:%M:%S',
                                         time.localtime(now)),
                     len(series), interval))
        out.write('%-46s %-8s %12s %12s  %s\n'
                  % ('stat', 'kind', 'last', 'per_sec', 'trend'))
        for n in sorted(series):
            s = series[n]
            pts = s['points']
            if s['kind'] == 'counter':
                deltas = [d for _t, _s, d in ts.counter_deltas(pts)]
                rate = ts.rate_per_s(pts)
                if not deltas or not any(deltas):
                    continue    # idle counters only add noise live
                out.write('%-46s %-8s %12s %12s  %s\n'
                          % (n, 'counter', _fmt(pts[-1][2]),
                             '-' if rate is None else '%.4g' % rate,
                             ts.spark(deltas)))
            elif s['kind'] == 'gauge':
                st = ts.gauge_stats(pts)
                vals = [p[2] for p in pts if p[2] is not None]
                out.write('%-46s %-8s %12s %12s  %s\n'
                          % (n, 'gauge', _fmt(st['last']), '-',
                             ts.spark(vals)))
            else:
                hw = ts.hist_window(s.get('edges') or (), pts)
                rate = hw.get('count', 0)
                elapsed = pts[-1][0] - pts[0][0] if len(pts) > 1 else 0
                per_s = (rate / elapsed) if elapsed > 0 else None
                means = [(b[3] - a[3]) / (b[2] - a[2])
                         for a, b in zip(pts, pts[1:])
                         if b[2] > a[2]]
                if not means:
                    continue
                mean_s = hw['mean']
                out.write('%-46s %-8s %12s %12s  %s\n'
                          % (n, 'hist',
                             '-' if mean_s is None
                             else '%.4g' % mean_s,
                             '-' if per_s is None else '%.4g' % per_s,
                             ts.spark(means)))
        out.flush()
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == '--watch':
        iters = None
        if '--iterations' in argv:
            i = argv.index('--iterations')
            if i + 1 >= len(argv):
                sys.stderr.write(__doc__)
                return 2
            iters = int(argv[i + 1])
            del argv[i:i + 2]
        if len(argv) != 3:
            sys.stderr.write(__doc__)
            return 2
        return watch(float(argv[1]), argv[2], iterations=iters)
    if argv and argv[0] == '--ops':
        if len(argv) != 2:
            sys.stderr.write(__doc__)
            return 2
        return ops_report(load_last(argv[1]))
    if argv and argv[0] == '--verify':
        if len(argv) != 2:
            sys.stderr.write(__doc__)
            return 2
        return verify_report(load_last(argv[1]))
    if argv and argv[0] == '--memory':
        if len(argv) != 2:
            sys.stderr.write(__doc__)
            return 2
        return memory_report(load_last(argv[1]))
    if argv and argv[0] == '--autoshard':
        if len(argv) != 2:
            sys.stderr.write(__doc__)
            return 2
        return autoshard_report(load_last(argv[1]))
    if argv and argv[0] == '--plan':
        if len(argv) != 2:
            sys.stderr.write(__doc__)
            return 2
        return plan_report(load_last(argv[1]))
    if argv and argv[0] == '--steps':
        rank = None
        if '--rank' in argv:
            i = argv.index('--rank')
            if i + 1 >= len(argv):
                sys.stderr.write(__doc__)
                return 2
            rank = argv[i + 1]
            del argv[i:i + 2]
        if len(argv) != 2:
            sys.stderr.write(__doc__)
            return 2
        return steps_report(argv[1], rank=rank)
    if argv == ['--live']:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
        from paddle_tpu.fluid import monitor
        rec = {'counters': monitor._counters, 'gauges': monitor._gauges,
               'histograms': {n: {'count': h[3], 'sum': h[2]}
                              for n, h in monitor._hists.items()}}
        render(rec)
        return 0
    if len(argv) == 1:
        render(load_last(argv[0]))
        return 0
    if len(argv) == 2:
        diff(load_last(argv[0]), load_last(argv[1]))
        return 0
    sys.stderr.write(__doc__)
    return 2


if __name__ == '__main__':
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `stat_summary.py x.jsonl | head`
        sys.exit(0)
