"""Minimal reproducer for the 'lenet b512' compile wedge (round-3
BENCHMARKS.md; root-caused round 4).

The trigger is NOT the batch-512 program fingerprint but this exact
pattern: an f32 conv2d WEIGHT-gradient (dW) computed at multi-pass MXU
precision (jax.lax.Precision.HIGHEST or HIGH — the 6-/3-pass bf16
emulation algorithms) whose cotangent arrives from fused elementwise
producers (a relu-grad select and/or a bias-grad reduce).  On the axon
TPU v5e compile service this hangs the compile RPC (>150 s, never
returns) for LeNet-conv1-shaped dW at batch 128, 256 and 512, while

  - batch 500 compiles in ~14 s (the round-3 bench fallback worked by
    accident of shape, not because 512 is special),
  - Precision.DEFAULT (single-pass bf16) always compiles in ~15 s,
  - the same dW WITHOUT a fused producer compiles (slowly, ~57 s),
  - the data-gradient (dImg) side alone always compiles.

Run on the attached TPU:

  python tools/repro_conv_wedge.py 512 highest   # hangs (ctrl-C / timeout)
  python tools/repro_conv_wedge.py 512 default   # ~15 s, OK
  python tools/repro_conv_wedge.py 500 highest   # ~14 s, OK

Framework mitigation: FLAGS_conv_precision ('highest'|'high'|'default')
selects the f32 conv algorithm; bench.py's lenet entry falls back to
'default' at the SAME batch when the compile deadline fires.
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    prec = {'highest': jax.lax.Precision.HIGHEST,
            'high': jax.lax.Precision.HIGH,
            'default': jax.lax.Precision.DEFAULT}[
        sys.argv[2] if len(sys.argv) > 2 else 'highest']
    rng = np.random.RandomState(0)
    ct = jnp.asarray(rng.rand(batch, 20, 24, 24).astype('float32'))
    y = jnp.asarray(rng.rand(batch, 20, 24, 24).astype('float32') - .5)
    img = jnp.asarray(rng.rand(batch, 1, 28, 28).astype('float32'))
    w = jnp.asarray(rng.randn(20, 1, 5, 5).astype('float32') * 0.1)

    def conv(im, ww):
        return jax.lax.conv_general_dilated(
            im, ww, (1, 1), 'VALID',
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
            precision=prec)

    def dw_with_fused_producer(ct, img, w):
        d = jnp.where(y > 0, ct, 0.0)        # relu_grad
        dbias = jnp.sum(d, (0, 2, 3))        # bias grad
        _, vjp = jax.vjp(lambda ww: conv(img, ww), w)
        return (dbias,) + vjp(d)

    print('compiling dW conv b%d precision=%s ...'
          % (batch, sys.argv[2] if len(sys.argv) > 2 else 'highest'),
          flush=True)
    t0 = time.time()
    out = jax.jit(dw_with_fused_producer)(ct, img, w)
    np.asarray(out[0]).ravel()[:1]
    print('compiled + ran in %.0f s' % (time.time() - t0))


if __name__ == '__main__':
    main()
