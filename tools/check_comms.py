"""Job-wide observability gate: the cross-worker trace collection,
collective telemetry and comms cost-model calibrator must work against
REAL processes (the fluid.comms analog of check_health.py's live-
endpoint checks).

Four postures:

  1. a real two-process collective job (tests/comms_worker.py x2, each
     a GradAllReduce program on its own 8-device CPU mesh, rank 0
     aggregating): trace.collect_job() must yield ONE schema-valid
     merged Perfetto timeline with both ranks' spans on per-rank
     process tracks and a shared clock, tolerating nothing worse than
     per-event noise; the aggregator's /statusz must carry the per-
     rank job view with a skew report; /trace/collect must serve the
     merged doc over HTTP;
  2. collective telemetry: both workers' /metrics.json must show
     nonzero comms/bytes_on_wire and populated per-(collective,
     size-bucket) bandwidth histograms, and the merged /metrics blob
     must stay fluid.health lint-clean; the workers run with the
     collective planner's quantized arm enabled (FLAGS_comms_quantize
     + a low floor), so each rank must ALSO show nonzero
     comms/plan_arm/* counters (the planner ran), plan wire bytes
     strictly below the dense-equivalent bytes (the quantized arm
     moved less than flat dense would have), and a populated
     comms_plan section in /statusz (the active plan per program);
  3. calibrator: tools/comms_calibrate.py --quick must emit a
     well-formed comms_model.json whose predicted times stay within
     2x of measured for every swept size — including the
     allreduce_quant entry that prices the quantized arm;
  4. disabled-path cost: with the tracer off, the steady-state
     hot-path budgets of tools/check_hot_path.py must still hold.

Run from `make check` (CPU: JAX_PLATFORMS=cpu; the tool forces the
8-device host platform itself).
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_RATIO = float(os.environ.get('PADDLE_TPU_COMMS_MAX_RATIO', 2.0))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _wait_ready(proc, url, deadline):
    while time.time() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read().decode('utf-8', 'replace') \
                if proc.stdout else ''
            raise RuntimeError('worker died rc=%d: %s'
                               % (proc.returncode, out[-800:]))
        try:
            code, _ = _get(url + '/healthz/local', timeout=2)
            if code == 200:
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise RuntimeError('worker at %s never became ready' % url)


def check_merged_timeline(doc, failures):
    events = doc.get('traceEvents')
    if not isinstance(events, list) or not events:
        failures.append('merged job timeline has no traceEvents')
        return
    rank_pids = {}
    for e in events:
        if not isinstance(e, dict):
            failures.append('non-dict trace event in merged timeline')
            return
        if e.get('ph') == 'X':
            for k in ('ts', 'dur', 'pid', 'name'):
                if k not in e:
                    failures.append('X event missing %r' % k)
                    return
            rank_pids.setdefault(e['pid'] // 100, set()).add(e['pid'])
    if len(rank_pids) < 2:
        failures.append('merged timeline has spans from %d rank '
                        'bands, wanted 2' % len(rank_pids))
    # shared clock: both ranks' span windows must overlap (the workers
    # step concurrently; a broken re-home puts them eras apart)
    spans_by_band = {}
    for e in events:
        if isinstance(e, dict) and e.get('ph') == 'X':
            spans_by_band.setdefault(e['pid'] // 100, []).append(
                (e['ts'], e['ts'] + e.get('dur', 0)))
    bands = sorted(spans_by_band)
    if len(bands) >= 2:
        a = spans_by_band[bands[0]]
        b = spans_by_band[bands[1]]
        a0, a1 = min(t for t, _ in a), max(t for _, t in a)
        b0, b1 = min(t for t, _ in b), max(t for _, t in b)
        if a1 < b0 or b1 < a0:
            failures.append(
                'rank clocks do not overlap after re-home '
                '(rank0 [%0.f, %.0f] vs rank1 [%.0f, %.0f] us)'
                % (a0, a1, b0, b1))
    ranks = {str(r.get('rank')) for r in doc.get('ptSteps', [])}
    if len(ranks) < 2:
        failures.append('merged ptSteps cover ranks %s, wanted 2'
                        % sorted(ranks))


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    sys.path.insert(0, ROOT)
    failures = []

    # ---- 1+2: real two-process collective job --------------------------
    worker = os.path.join(ROOT, 'tests', 'comms_worker.py')
    p0, p1 = _free_port(), _free_port()
    spec = '0=127.0.0.1:%d,1=127.0.0.1:%d' % (p0, p1)
    base_env = dict(os.environ)
    base_env.update({'PADDLE_TPU_STATUS_WORKERS': spec,
                     'FLAGS_health_heartbeat_seconds': '0.5',
                     'FLAGS_trace': '1',
                     # collective-planner posture: quantized arm on
                     # with a floor below the worker's grad-bucket
                     # size, so the planner must fire and the wire
                     # bytes must drop vs dense
                     'FLAGS_comms_quantize': '1',
                     'FLAGS_comms_quantize_min_bytes': '1024'})
    env0 = dict(base_env, PADDLE_TRAINER_ID='0',
                PADDLE_TPU_STATUS_AGGREGATE='1')
    env1 = dict(base_env, PADDLE_TRAINER_ID='1',
                PADDLE_TPU_STATUS_AGGREGATE='0')
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p1), '120'], env=env1,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p0), '120'], env=env0,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        deadline = time.time() + 240
        agg = 'http://127.0.0.1:%d' % p0
        wrk = 'http://127.0.0.1:%d' % p1
        _wait_ready(procs[0], wrk, deadline)
        _wait_ready(procs[1], agg, deadline)
        time.sleep(2.0)     # a few steps + one heartbeat of scrapes

        from paddle_tpu.fluid import trace as pt_trace
        from paddle_tpu.fluid import health as pt_health
        doc = pt_trace.collect_job(workers=spec)
        if doc['ptJob']['skipped']:
            failures.append('collect_job skipped healthy workers: %r'
                            % doc['ptJob']['skipped'])
        check_merged_timeline(doc, failures)

        # collect over HTTP too: the aggregator's /trace/collect must
        # serve the same merged document shape
        code, body = _get(agg + '/trace/collect', timeout=30)
        if code != 200:
            failures.append('/trace/collect returned %d' % code)
        else:
            hdoc = json.loads(body)
            if len(hdoc.get('ptJob', {}).get('workers', {})) < 2:
                failures.append('/trace/collect merged %d workers, '
                                'wanted 2' % len(
                                    hdoc.get('ptJob', {})
                                    .get('workers', {})))

        # per-worker comms telemetry: nonzero bytes, bw histograms
        for name, url in (('rank0', agg), ('rank1', wrk)):
            code, body = _get(url + '/metrics.json')
            state = json.loads(body)['state']
            counters = state['counters']
            if counters.get('comms/bytes_on_wire', 0.0) <= 0:
                failures.append('%s comms/bytes_on_wire is zero'
                                % name)
            hists = [h for h in state['hists']
                     if h.startswith('comms/bw_gbps/')]
            if not any(state['hists'][h]['count'] > 0 for h in hists):
                failures.append('%s has no populated comms/bw_gbps/* '
                                'histogram' % name)
            # collective planner: the quantized arm must have run, and
            # its wire bytes must be strictly below what flat dense
            # would have moved (the named saving, not a claim)
            arm_hits = sum(v for k, v in counters.items()
                           if k.startswith('comms/plan_arm/'))
            if arm_hits <= 0:
                failures.append('%s comms/plan_arm/* counters are '
                                'zero: planner never ran' % name)
            if counters.get('comms/plan_arm/quant', 0.0) <= 0:
                failures.append('%s quantized arm never fired despite '
                                'FLAGS_comms_quantize' % name)
            plan_wire = counters.get('comms/plan_wire_bytes', 0.0)
            dense_equiv = counters.get('comms/plan_dense_equiv_bytes',
                                       0.0)
            if not (0 < plan_wire < 0.5 * dense_equiv):
                failures.append(
                    '%s planned wire bytes did not drop vs dense '
                    '(%.0f vs dense-equiv %.0f)'
                    % (name, plan_wire, dense_equiv))
            # /statusz must carry the active plan per program
            code, body = _get(url + '/statusz')
            plan_sec = json.loads(body).get('comms_plan')
            if not plan_sec or not plan_sec.get('programs'):
                failures.append('%s /statusz comms_plan section '
                                'missing or empty' % name)
            else:
                buckets = [b for p in plan_sec['programs'].values()
                           for b in p.get('buckets', [])]
                if not any(b.get('grads', 0) > 1 for b in buckets):
                    failures.append('%s /statusz comms_plan shows no '
                                    'fused bucket' % name)
                # the transpile-time preview must agree with the
                # posture: quantize is on with a floor below the
                # bucket size, so the preview names the quant arm
                if not any(b.get('arm_preview') == 'quant'
                           for b in buckets):
                    failures.append('%s /statusz comms_plan preview '
                                    'never shows the quant arm'
                                    % name)

        # merged /metrics stays lint-clean with the comms/* families
        code, body = _get(agg + '/metrics')
        problems = pt_health.prom_lint(body.decode('utf-8'))
        if problems:
            failures.append('merged /metrics lint: %s' % problems[:5])
        if 'paddle_tpu_comms_bytes_on_wire' not in body.decode('utf-8'):
            failures.append('merged /metrics missing comms series')

        # aggregator /statusz: per-rank liveness + skew report
        code, body = _get(agg + '/statusz')
        job = json.loads(body).get('job')
        if not job or len(job.get('workers', {})) < 2:
            failures.append('/statusz job section missing or short: %r'
                            % (job and sorted(job.get('workers', {}))))
        else:
            skew = job.get('skew')
            if not skew or skew['wall']['skew_ratio'] < 1.0:
                failures.append('/statusz job skew missing/invalid: %r'
                                % (skew,))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass

    # ---- 3: calibrator --------------------------------------------------
    model_path = os.path.join(tempfile.mkdtemp(prefix='pt_comms_'),
                              'comms_model.json')
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools',
                                      'comms_calibrate.py'),
         '--quick', '--out', model_path],
        env=dict(os.environ), capture_output=True, text=True,
        timeout=900)
    if r.returncode != 0:
        failures.append('comms_calibrate.py failed: %s'
                        % r.stderr[-500:])
    else:
        try:
            model = json.load(open(model_path))
            colls = model['collectives']
            assert model['devices'] >= 2 and colls
            if 'allreduce_quant' not in colls:
                failures.append('comms_model.json has no '
                                'allreduce_quant entry: the quantized '
                                'arm was not calibrated')
            for kind, entry in colls.items():
                assert entry['inv_bw_s_per_byte'] > 0
                assert entry['latency_s'] >= 0
                assert entry['points']
                if entry['max_ratio'] > MAX_RATIO:
                    failures.append(
                        'calibrator %s predicted/measured ratio '
                        '%.2fx exceeds %.1fx'
                        % (kind, entry['max_ratio'], MAX_RATIO))
        except Exception as e:
            failures.append('comms_model.json malformed: %s' % e)

    # ---- 4: disabled-path hot-loop budgets ------------------------------
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools',
                                      'check_hot_path.py')],
        env=dict(os.environ), capture_output=True, text=True,
        timeout=600)
    if r.returncode != 0:
        failures.append('check_hot_path budgets broke with comms '
                        'telemetry in the tree:\n%s'
                        % (r.stdout + r.stderr)[-800:])

    if failures:
        print('check_comms: FAIL')
        for f in failures:
            print('  - %s' % f)
        return 1
    print('check_comms: merged 2-rank timeline OK, comms telemetry '
          'nonzero + lint-clean, planner ran (quant arm, wire < '
          'dense-equiv, /statusz plan), calibrator (incl. quant arm) '
          'within %.1fx, hot-path budgets hold' % MAX_RATIO)
    return 0


if __name__ == '__main__':
    sys.exit(main())
