"""Op-coverage audit: our registry vs the reference's REGISTER_OPERATOR
names (the CI-gate analog of reference tools/check_op_desc.py /
diff_api.py).

Classifies every reference op as: registered here, synthesized (*_grad
— gradients come from jax.vjp, ops/registry.py grad_op_def, so grad ops
are never separately registered), or replaced-by-design (subgraph engine
ops whose role XLA itself fills).  Exits nonzero if any reference op is
genuinely uncovered.
"""

import os
import re
import subprocess
import sys

REFERENCE = os.environ.get('PADDLE_REFERENCE', '/root/reference')

# subgraph-engine + infra ops whose role the XLA compiler itself fills
REPLACED = {
    'tensorrt_engine': 'XLA is the engine (no TRT subgraphs)',
    'ngraph_engine': 'XLA is the engine',
    'anakin_engine': 'XLA is the engine',
    'lite_engine': 'XLA is the engine',
    'fusion_group': 'XLA fusion + Pallas kernels replace NVRTC JIT',
    'gen_nccl_id': 'jax.distributed rendezvous replaces NCCL id bcast',
    'listen_and_serv': 'embedded PS store + communicator '
                       '(incubate/fleet/parameter_server)',
    'recv_save': 'save_persistables on the embedded store',
    'op_name': 'grep artifact (macro arg, not an op)',
    'op_type': 'grep artifact (macro arg, not an op)',
}


def reference_ops():
    out = subprocess.run(
        ['grep', '-rhoE', r'REGISTER_OPERATOR\(\s*[a-z0-9_]+',
         os.path.join(REFERENCE, 'paddle/fluid/operators/')],
        capture_output=True, text=True).stdout
    return set(re.findall(r'REGISTER_OPERATOR\(\s*([a-z0-9_]+)', out))


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.ops import registry
    ours = set(registry.registered_ops())
    ref = reference_ops()

    grad = {n for n in ref - ours
            if n.endswith('_grad') or '_grad_grad' in n
            or n.endswith('_grad2')}
    replaced = {n for n in ref - ours - grad if n in REPLACED}
    missing = sorted(ref - ours - grad - replaced)

    print('reference ops: %d' % len(ref))
    print('registered here: %d (+%d extras beyond the reference)'
          % (len(ref & ours), len(ours - ref)))
    print('grad ops synthesized via jax.vjp: %d' % len(grad))
    for n in sorted(replaced):
        print('replaced-by-design: %-24s %s' % (n, REPLACED[n]))
    if missing:
        print('MISSING (%d): %s' % (len(missing), missing))
        return 1
    print('coverage: complete')
    return 0


if __name__ == '__main__':
    sys.exit(main())
