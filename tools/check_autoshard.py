"""Auto-sharding planner gate: FLAGS_auto_shard must plan REAL jobs
(the parallel/plan.py analog of check_comms.py's live two-process
posture).

Three postures:

  1. a real two-process collective job (tests/comms_worker.py x2 with
     FLAGS_auto_shard=1): BOTH ranks must show populated
     parallel/plan_* counters in /metrics.json (the planner ran in
     every process, not just rank 0) and an auto_shard section in
     /statusz naming the chosen layout and its priced candidates;
  2. flag-off hygiene: with FLAGS_auto_shard=0 a hand-placed mesh
     program must train BIT-FOR-BIT identically whether or not the
     planner machinery was exercised in the same process (the planner
     leaves no residue), and the global digest must be the constant
     'auto_shard(off)' so segment fingerprints are unchanged;
  3. flag-on: an UNANNOTATED program must reach a sharded mesh at
     loss parity with the hand-placed baseline, with the plan
     registered and the layout gauges populated.

Run from `make check` (CPU: JAX_PLATFORMS=cpu; the tool forces the
8-device host platform itself).
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# one implementation of the two-process scaffolding: port pick, HTTP
# get, and worker readiness live in check_comms — a fix to worker
# cleanup/readiness there must not silently diverge here
from check_comms import _free_port, _get, _wait_ready  # noqa: E402


def check_two_process_job(failures):
    worker = os.path.join(ROOT, 'tests', 'comms_worker.py')
    p0, p1 = _free_port(), _free_port()
    spec = '0=127.0.0.1:%d,1=127.0.0.1:%d' % (p0, p1)
    base_env = dict(os.environ)
    base_env.update({'PADDLE_TPU_STATUS_WORKERS': spec,
                     'FLAGS_health_heartbeat_seconds': '0.5',
                     'FLAGS_auto_shard': '1'})
    env0 = dict(base_env, PADDLE_TRAINER_ID='0',
                PADDLE_TPU_STATUS_AGGREGATE='1')
    env1 = dict(base_env, PADDLE_TRAINER_ID='1',
                PADDLE_TPU_STATUS_AGGREGATE='0')
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p1), '120'], env=env1,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p0), '120'], env=env0,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        deadline = time.time() + 240
        agg = 'http://127.0.0.1:%d' % p0
        wrk = 'http://127.0.0.1:%d' % p1
        _wait_ready(procs[0], wrk, deadline)
        _wait_ready(procs[1], agg, deadline)

        for name, url in (('rank0', agg), ('rank1', wrk)):
            code, body = _get(url + '/metrics.json')
            counters = json.loads(body)['state']['counters']
            if counters.get('parallel/plan_builds', 0.0) <= 0:
                failures.append('%s parallel/plan_builds is zero: '
                                'the planner never ran' % name)
            if counters.get('parallel/plan_candidates', 0.0) <= 0:
                failures.append('%s parallel/plan_candidates is '
                                'zero' % name)
            code, body = _get(url + '/statusz')
            sec = json.loads(body).get('auto_shard')
            if not sec or not sec.get('enabled'):
                failures.append('%s /statusz auto_shard section '
                                'missing or disabled' % name)
            elif not sec.get('programs'):
                failures.append('%s /statusz auto_shard names no '
                                'planned program' % name)
            else:
                prog = next(iter(sec['programs'].values()))
                if not prog.get('candidates'):
                    failures.append('%s auto_shard plan carries no '
                                    'priced candidates' % name)
                if 'layout' not in prog or 'digest' not in prog:
                    failures.append('%s auto_shard plan missing '
                                    'layout/digest' % name)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


def check_in_process(failures):
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor
    from paddle_tpu.parallel import mesh as pmesh
    from paddle_tpu.parallel import plan

    def build(seed=9):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[32], dtype='float32')
            y = layers.data('y', shape=[1], dtype='float32')
            h = layers.fc(x, 64, act='relu')
            h = layers.fc(h, 64, act='relu')
            loss = layers.reduce_mean(layers.square_error_cost(
                layers.fc(h, 1), y))
            fluid.optimizer.Adam(0.01).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    feed = {'x': rng.rand(16, 32).astype('float32'),
            'y': rng.rand(16, 1).astype('float32')}

    def run_hand(steps=4):
        mesh = pmesh.create_mesh(dp=8)
        main, startup, loss = build()
        comp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name).with_mesh(mesh)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            return [np.asarray(exe.run(comp, feed=feed,
                                       fetch_list=[loss])[0]).copy()
                    for _ in range(steps)]

    # --- posture 2: flag off is bit-for-bit, planner leaves no residue
    fluid.set_flags({'FLAGS_auto_shard': False})
    baseline = run_hand()
    if plan.digest() != 'auto_shard(off)':
        failures.append('flag-off digest is %r, wanted the constant'
                        % plan.digest())
    # exercise the planner on a throwaway program, then repeat the
    # hand-placed run with the flag back off
    fluid.set_flags({'FLAGS_auto_shard': True})
    m2, s2, l2 = build(seed=11)
    comp2 = fluid.CompiledProgram(m2).with_data_parallel(
        loss_name=l2.name)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(s2)
        exe.run(comp2, feed=feed, fetch_list=[l2])
    fluid.set_flags({'FLAGS_auto_shard': False})
    again = run_hand()
    for a, b in zip(baseline, again):
        if not np.array_equal(a, b):
            failures.append('FLAGS_auto_shard=0 run diverged from the '
                            'hand-placed baseline after the planner '
                            'ran in-process (%r vs %r)' % (a, b))
            break

    # --- posture 3: flag on, unannotated program, parity + plan
    monitor.reset()
    plan.reset()
    fluid.set_flags({'FLAGS_auto_shard': True})
    main, startup, loss = build()
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        auto = [np.asarray(exe.run(comp, feed=feed,
                                   fetch_list=[loss])[0]).copy()
                for _ in range(4)]
    if not np.allclose(np.ravel(auto), np.ravel(baseline),
                       rtol=5e-3, atol=5e-4):
        failures.append('auto-shard losses diverge from hand-placed '
                        'baseline: %r vs %r' % (auto, baseline))
    if monitor.counter_value('parallel/plan_builds') < 1:
        failures.append('flag-on run never built a plan')
    dp = monitor.gauge_value('parallel/plan_layout_dp')
    fsdp = monitor.gauge_value('parallel/plan_layout_fsdp')
    tp = monitor.gauge_value('parallel/plan_layout_tp')
    if dp * fsdp * tp != 8:
        failures.append('plan layout gauges dp=%g fsdp=%g tp=%g do '
                        'not cover the 8-device mesh' % (dp, fsdp, tp))
    fluid.set_flags({'FLAGS_auto_shard': False})


def main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    sys.path.insert(0, ROOT)
    failures = []
    check_two_process_job(failures)
    check_in_process(failures)
    if failures:
        print('check_autoshard: FAIL')
        for f in failures:
            print('  - %s' % f)
        return 1
    print('check_autoshard: two-process job planned on both ranks '
          '(parallel/plan_* counters + /statusz auto_shard), flag-off '
          'bit-for-bit with the hand-placed baseline, flag-on '
          'unannotated program sharded at loss parity')
    return 0


if __name__ == '__main__':
    sys.exit(main())
